"""autoplan — cost-model-driven auto-sharding (the ISSUE-19 suite).

Covers the search end to end:
  * candidate enumeration (mesh factorings, placement families) and the
    deterministic ranking contract;
  * pruning happens BEFORE any compile: with a tiny forced HBM capacity
    every candidate dies as MC001 and ``executor.traces`` stays flat —
    OOM-doomed plans provably never reach XLA; hand-invalid plans die as
    sc_invalid with their SC codes attached;
  * ``plan="auto"`` wiring: one trace total across steady state, the
    resolve memo returns the SAME plan object for repeat programs (no
    re-search), a fresh Executor warm-starts from the persistent compile
    cache under the auto plan, and a memo-reset re-search lands on the
    identical fingerprint (the cross-process determinism the disk cache
    keys on);
  * ledger drift corrections: median(measured/predicted) per leg from raw
    records, clamped to the correction band — and a pinned fixture where
    applying a comm-leg correction flips which plan wins the search;
  * satellite 1: ``shardcheck.estimate_comm`` prices the embedding
    all_to_all exchange with the same math as ``emb.exchange_bytes`` and
    lands within a 2x band of the traced observation;
  * satellite 2: the ``estimate_peak_cached`` memo is a bounded ring with
    recency refresh — hot keys survive the cap, the oldest insertion is
    evicted (regression: the old clear-on-cap dropped everything);
  * elastic replan: ``failover.replan_for_survivors`` searches the
    truncated world and flight-records ``autoplan_replan``;
  * fleet strategy plumbing and the CLI selfcheck (subprocess rider:
    reproduce-or-beat hand plans + execution parity on the 8-device CPU
    mesh).
"""
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu.static as static
import paddle_tpu.static.shardcheck as sc
from paddle_tpu.core import flags
from paddle_tpu.elastic import failover
from paddle_tpu.parallel import autoplan
from paddle_tpu.parallel import embedding as pemb
from paddle_tpu.parallel import fleet
from paddle_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from paddle_tpu.parallel.sharding import ShardingPlan
from paddle_tpu.static import layers as L
from paddle_tpu.static import memcheck
from paddle_tpu.utils import monitor
from paddle_tpu.utils import trace as trace_mod

_REPO = Path(__file__).resolve().parents[1]

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU mesh")


def _mesh(dp: int, tp: int) -> Mesh:
    devs = np.asarray(jax.devices()[:dp * tp]).reshape(dp, tp)
    return Mesh(devs, (DP_AXIS, TP_AXIS))


def _fc_tower(hidden=16, batch=16):
    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with static.program_guard(main, startup):
        x = L.data("x", [hidden])
        y = L.data("y", [1])
        h = L.fc(x, hidden, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square(L.elementwise_sub(pred, y)))
        static.optimizer.SGD(learning_rate=0.05).minimize(loss)
    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(size=(batch, hidden)).astype(np.float32),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}
    return main, startup, loss, feed


def _ctr(vocab=64, dim=8):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = L.data("ids", [], dtype="int64")
        y = L.data("y", [1])
        emb = L.embedding(ids, size=[vocab, dim], name="xch_emb")
        pred = L.fc(emb, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# enumeration + deterministic ranking
# ---------------------------------------------------------------------------

def test_mesh_factorings():
    assert autoplan.mesh_factorings(8) == [(8, 1), (4, 2), (2, 4), (1, 8)]
    assert autoplan.mesh_factorings(1) == [(1, 1)]
    assert autoplan.mesh_factorings(6) == [(6, 1), (3, 2), (2, 3), (1, 6)]


@needs_devices
def test_search_is_deterministic_and_ranked():
    main, _startup, loss, feed = _fc_tower()
    shapes = {k: v.shape for k, v in feed.items()}
    neutral = {"comm": 1.0, "mem": 1.0, "roofline": 1.0}
    a = autoplan.search(main, devices=jax.devices()[:8], feed_shapes=shapes,
                        fetch_names=(loss.name,), corrections=neutral)
    b = autoplan.search(main, devices=jax.devices()[:8], feed_shapes=shapes,
                        fetch_names=(loss.name,), corrections=neutral)
    assert a.ranked, "no viable candidate on an 8-device mesh"
    assert a.best.fingerprint() == b.best.fingerprint()
    assert [c.plan.fingerprint() for c in a.ranked] \
        == [c.plan.fingerprint() for c in b.ranked]
    scores = [c.score for c in a.ranked]
    assert scores == sorted(scores)
    # the ranked report renders and round-trips
    assert "rank" in a.render(top=5)
    doc = a.to_dict()
    assert doc["candidates"] and doc["program"] == a.program_fp


# ---------------------------------------------------------------------------
# pruning happens BEFORE any compile
# ---------------------------------------------------------------------------

@needs_devices
def test_mc001_oom_doomed_candidates_never_compile():
    main, _startup, loss, feed = _fc_tower()
    shapes = {k: v.shape for k, v in feed.items()}
    reg = monitor.default_registry()
    traces = reg.get("executor.traces")
    cand_counter = reg.get("autoplan.candidates")
    t0 = traces.value()
    oom0 = cand_counter.value(status="mc_oom")
    saved = flags.get_flags(["memcheck_capacity_gb"])
    try:
        # ~10 bytes of HBM: even fully zero-3-sharded state cannot fit
        flags.set_flags({"memcheck_capacity_gb": 1e-8})
        choice = autoplan.search(
            main, devices=jax.devices()[:8], feed_shapes=shapes,
            fetch_names=(loss.name,),
            corrections={"comm": 1.0, "mem": 1.0, "roofline": 1.0})
    finally:
        flags.set_flags(saved)
    assert choice.best is None and not choice.ranked
    assert choice.pruned
    assert all(c.status == "mc_oom" and c.pruned_codes == ("MC001",)
               for c in choice.pruned)
    assert traces.value() == t0, "a pruned candidate reached the tracer"
    assert cand_counter.value(status="mc_oom") - oom0 == len(choice.pruned)
    # and resolve_auto surfaces the dead end instead of compiling anyway
    saved = flags.get_flags(["memcheck_capacity_gb"])
    try:
        flags.set_flags({"memcheck_capacity_gb": 1e-8})
        autoplan.reset_auto_cache()
        with pytest.raises(ValueError, match="MC001"):
            autoplan.resolve_auto(main, mesh=_mesh(1, 8), feed=feed,
                                  fetch_names=(loss.name,))
    finally:
        flags.set_flags(saved)
        autoplan.reset_auto_cache()
    assert traces.value() == t0


@needs_devices
def test_sc_invalid_plan_pruned_with_codes():
    main, _startup, loss = _ctr()
    # embedding sharded over the batch axis: SC010 by construction
    bad = ShardingPlan(mesh=_mesh(8, 1), embedding_shard=DP_AXIS,
                       batch_axes=(DP_AXIS,))
    reg = monitor.default_registry()
    traces = reg.get("executor.traces")
    t0 = traces.value()
    cand = autoplan.score_plan(main, bad, feed_shapes={"ids": (16,),
                                                       "y": (16, 1)},
                               fetch_names=(loss.name,),
                               corrections={"comm": 1.0, "mem": 1.0,
                                            "roofline": 1.0})
    assert cand.status == "sc_invalid"
    assert "SC010" in cand.pruned_codes
    assert cand.score is None
    assert traces.value() == t0


# ---------------------------------------------------------------------------
# plan="auto": zero steady-state retraces, memoized resolution, warm starts
# ---------------------------------------------------------------------------

@needs_devices
def test_plan_auto_zero_steady_state_retraces_and_memo(tmp_path):
    main, startup, loss, feed = _fc_tower()
    reg = monitor.default_registry()
    traces = reg.get("executor.traces")
    searches = reg.get("autoplan.searches")
    autoplan.reset_auto_cache()
    saved = flags.get_flags(["compile_cache_dir"])
    try:
        flags.set_flags({"compile_cache_dir": str(tmp_path)})

        def one_run(steps=5):
            scope = static.Scope()
            with static.scope_guard(scope):
                exe = static.Executor()
                exe.run(startup)
                comp = static.CompiledProgram(main).with_sharding(plan="auto")
                out = [float(np.asarray(exe.run(comp, feed=feed,
                                                fetch_list=[loss])[0]))
                       for _ in range(steps)]
            return out, comp._plan

        t0, s0 = traces.value(), searches.value()
        losses, plan = one_run()
        assert plan is not None and searches.value() - s0 == 1
        # exactly two traces: the startup program + the auto-planned step;
        # 4 more steady-state steps add nothing
        assert traces.value() - t0 == 2, "steady state under plan='auto' " \
            "retraced"
        assert losses[-1] < losses[0]  # it actually trains

        # a second CompiledProgram over the same program: the resolve memo
        # returns the SAME plan object (token-stable, no new search), and
        # the fresh Executor warm-starts from the persistent cache
        hits = reg.get("executor.compile_cache_hit")
        h0, t1, s1 = hits.value(), traces.value(), searches.value()
        losses2, plan2 = one_run()
        assert plan2 is plan
        assert searches.value() == s1, "memoized resolution re-searched"
        assert traces.value() == t1, "warm start re-traced python"
        assert hits.value() > h0, "warm start missed the persistent cache"
        assert losses2 == losses

        # memo reset -> the search re-runs but lands on the identical
        # fingerprint: what a restarted process keys the disk cache with
        autoplan.reset_auto_cache()
        _losses3, plan3 = one_run(steps=1)
        assert searches.value() - s1 == 1
        assert plan3.fingerprint() == plan.fingerprint()
    finally:
        flags.set_flags(saved)
        autoplan.reset_auto_cache()


@needs_devices
def test_fleet_auto_shard_strategy():
    main, _startup, loss, feed = _fc_tower()
    strategy = fleet.DistributedStrategy()
    assert fleet.auto_shard_plan(main, strategy) is None  # off by default
    strategy.auto_shard = True
    autoplan.reset_auto_cache()
    try:
        plan = fleet.auto_shard_plan(main, strategy, mesh=_mesh(1, 8),
                                     feed=feed, fetch_names=(loss.name,))
        assert isinstance(plan, ShardingPlan)
        # same resolution path as CompiledProgram(plan="auto"): memo hit
        comp = static.CompiledProgram(main).with_sharding(plan="auto",
                                                          mesh=_mesh(1, 8))
        assert comp._sharding_plan(feed=feed, fetch_list=[loss]) is plan
    finally:
        autoplan.reset_auto_cache()


# ---------------------------------------------------------------------------
# ledger drift corrections
# ---------------------------------------------------------------------------

def test_drift_corrections_median_and_clamp():
    def rec(program, comm_p, comm_m, mem_p, mem_m, ms_p, ms_m):
        return {"key": {"program": program},
                "predicted": {"comm_bytes": comm_p, "peak_hbm_bytes": mem_p,
                              "roofline_ms": ms_p},
                "measured": {"allreduce_bytes": comm_m,
                             "mem_total_bytes": mem_m,
                             "step_time_ms": ms_m}}

    recs = [rec("p1", 100, 50, 1000, 2000, 1.0, 4.0),
            rec("p1", 100, 150, 1000, 2000, 1.0, 6.0),
            rec("p1", 100, 100, 1000, 2000, 1.0, 5.0)]
    corr = autoplan.drift_corrections(records=recs)
    assert corr["comm"] == pytest.approx(1.0)     # median of 0.5/1.5/1.0
    assert corr["mem"] == pytest.approx(2.0)
    assert corr["roofline"] == pytest.approx(5.0)

    # program-filtered: p2's records dominate when asked for p2, the full
    # pool is the fallback prior for an unseen program
    recs.append(rec("p2", 100, 800, 1000, 1000, 1.0, 1.0))
    assert autoplan.drift_corrections("p2", records=recs)["comm"] \
        == pytest.approx(8.0)
    assert autoplan.drift_corrections("unseen", records=recs)["roofline"] \
        == pytest.approx(4.5)

    # clamped to the correction band; cold start is 1.0
    wild = [rec("p1", 1, 1e9, 1, 1e-9 + 1, 1.0, 1.0)]
    c = autoplan.drift_corrections(records=wild)
    assert c["comm"] == 16.0
    assert autoplan.drift_corrections(records=[]) \
        == {"comm": 1.0, "mem": 1.0, "roofline": 1.0}


@needs_devices
def test_drift_correction_flips_the_ranking():
    """The pinned fixture: on the fc tower over 8 devices the neutral
    search favors a tp-style plan (no gradient all-reduce on the wire);
    a ledger that has measured communication far cheaper than predicted
    (comm leg at the band floor) hands the win to a dp plan whose batch
    division pays off once its all-reduce is discounted."""
    main, _startup, loss, feed = _fc_tower(hidden=16, batch=16)
    shapes = {k: v.shape for k, v in feed.items()}
    neutral = autoplan.drift_corrections(records=[])
    cheap_comm = autoplan.drift_corrections(records=[
        {"key": {"program": "x"},
         "predicted": {"comm_bytes": 1e9},
         "measured": {"allreduce_bytes": 1.0}}])
    assert cheap_comm["comm"] == 1.0 / 16.0

    a = autoplan.search(main, devices=jax.devices()[:8], feed_shapes=shapes,
                        fetch_names=(loss.name,), corrections=neutral)
    b = autoplan.search(main, devices=jax.devices()[:8], feed_shapes=shapes,
                        fetch_names=(loss.name,), corrections=cheap_comm)
    assert a.ranked and b.ranked
    assert a.best.fingerprint() != b.best.fingerprint(), \
        "comm-leg correction did not change the winner"
    assert b.ranked[0].desc["dp"] > a.ranked[0].desc["dp"], \
        "discounted comm should push the win toward deeper batch division"


# ---------------------------------------------------------------------------
# satellite 1: the exchange-bytes leg of estimate_comm
# ---------------------------------------------------------------------------

@needs_devices
def test_estimate_comm_prices_embedding_exchange_within_2x():
    main, startup, loss = _ctr(vocab=64, dim=8)
    plan = ShardingPlan(mesh=_mesh(1, 8), embedding_shard=TP_AXIS,
                        donate=False)
    est = sc.estimate_comm(main, plan, feed_shapes={"ids": (16,),
                                                    "y": (16, 1)})
    # same math as the embedding module's own accounting (dp=1: all 16
    # ids are local)
    assert est.exchange_bytes == pemb.exchange_bytes(16, 8, 8)
    assert est.exchange_bytes > 0
    assert len(est.exchange_sites) == 1
    _site, table, n_local, nbytes = est.exchange_sites[0]
    assert table == "xch_emb.w" and n_local == 16
    assert nbytes == est.exchange_bytes
    assert est.total_bytes >= est.exchange_bytes
    assert est.to_dict()["exchange_bytes"] == est.exchange_bytes

    # the traced run observes the same wire bytes (2x band pins the
    # estimate to reality, not just to its own formula)
    rng = np.random.default_rng(0)
    feed = {"ids": rng.integers(0, 64, size=(16,)).astype(np.int64),
            "y": rng.normal(size=(16, 1)).astype(np.float32)}
    hist = monitor.default_registry().get("emb.exchange_bytes")
    s0, c0 = hist.sum(), hist.count()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        comp = static.CompiledProgram(main).with_sharding(plan=plan)
        exe.run(comp, feed=feed, fetch_list=[loss])
    observed_n = hist.count() - c0
    assert observed_n >= 1, "the sharded lookup never observed its wire"
    observed = (hist.sum() - s0) / observed_n
    assert observed / 2 <= est.exchange_bytes <= observed * 2

    # quantized backward wire shrinks the estimate too
    qplan = ShardingPlan(mesh=_mesh(1, 8), embedding_shard=TP_AXIS,
                         embedding_quantize="int8", donate=False)
    qest = sc.estimate_comm(main, qplan, feed_shapes={"ids": (16,),
                                                      "y": (16, 1)})
    assert 0 < qest.exchange_bytes < est.exchange_bytes


def test_estimate_comm_no_exchange_without_embedding_shard():
    main, _startup, _loss = _ctr()
    est = sc.estimate_comm(main, ShardingPlan(), feed_shapes={"ids": (16,)})
    assert est.exchange_bytes == 0 and est.exchange_sites == []


# ---------------------------------------------------------------------------
# satellite 2: the bounded-ring estimate memo
# ---------------------------------------------------------------------------

def test_estimate_peak_memo_bounded_ring_with_recency(monkeypatch):
    main, _startup, _loss, _feed = _fc_tower(hidden=8, batch=4)
    monkeypatch.setattr(memcheck, "_EST_MEMO", {})
    monkeypatch.setattr(memcheck, "_EST_MEMO_CAP", 3)
    checks = monitor.default_registry().get("analysis.mem_checks")

    def est(n):
        r = memcheck.estimate_peak_cached(main, None,
                                          feed_arrays={"x": (n, 8),
                                                       "y": (n, 1)})
        assert r is not None and r.peak_bytes > 0
        return r

    base = checks.value()
    for n in (2, 4, 6):
        est(n)
    assert checks.value() - base == 3        # three misses fill the ring
    est(2)                                   # hit + recency refresh
    assert checks.value() - base == 3
    est(8)                                   # at cap: evicts oldest (n=4)
    assert checks.value() - base == 4
    est(2)                                   # the refreshed key SURVIVED
    assert checks.value() - base == 4        # (old clear-on-cap dropped it)
    est(4)                                   # the evicted key re-misses
    assert checks.value() - base == 5
    assert len(memcheck._EST_MEMO) <= 3


# ---------------------------------------------------------------------------
# elastic replan
# ---------------------------------------------------------------------------

@needs_devices
def test_replan_for_survivors_truncates_world_and_records():
    main, _startup, loss, feed = _fc_tower()
    reg = monitor.default_registry()
    replans = reg.get("autoplan.replans")
    r0 = replans.value()
    choice = failover.replan_for_survivors(
        main, world=4,
        feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=(loss.name,))
    assert choice.best is not None
    assert choice.best.resolve_mesh().devices.size == 4
    assert replans.value() - r0 == 1
    ev = [e for e in trace_mod.flight_recorder().events()
          if e["kind"] == "autoplan_replan"]
    assert ev and ev[-1]["world"] == 4 and ev[-1]["name"] == "eviction"
    assert ev[-1]["chosen"] == choice.best.fingerprint()


# ---------------------------------------------------------------------------
# the CLI selfcheck rides tier-1
# ---------------------------------------------------------------------------

@needs_devices
def test_cli_selfcheck():
    """Subprocess probe: search reproduces-or-beats the hand plans on all
    demo models, prices without compiling, and executes the winner with
    loss parity + zero steady-state retraces (see tools/autoplan.py)."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.autoplan", "--selfcheck"],
        cwd=_REPO, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "autoplan selfcheck: OK" in r.stdout


@needs_devices
def test_cli_json_report():
    r = subprocess.run(
        [sys.executable, "-m", "tools.autoplan", "--model", "fc",
         "--format", "json", "--top", "3"],
        cwd=_REPO, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
    import json as _json

    doc = _json.loads(r.stdout)
    assert doc["best"] and doc["candidates"]
    assert doc["hand"]["desc"]["placement"] == "hand"
    statuses = {c["status"] for c in doc["candidates"]}
    assert "ok" in statuses
