"""Pipeline parallelism: circular ppermute schedule == sequential execution,
and gradients flow through the pipeline (SURVEY.md §2.2 "Pipeline
parallelism" — ref PipelineOptimizer fluid/optimizer.py:3661)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.collective import shard_map
from paddle_tpu.parallel.pipeline import (
    PipelineStage,
    blockwise_stage_fn,
    microbatch,
    pipeline_apply,
    stack_block_params,
    unmicrobatch,
)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_mod.set_mesh(None)


def _block_fn(blk, x):
    return jnp.tanh(x @ blk["w"] + blk["b"])


def _make_blocks(n_blocks, d, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rng.normal(0, 0.5, (d, d)), jnp.float32),
             "b": jnp.asarray(rng.normal(0, 0.1, (d,)), jnp.float32)}
            for _ in range(n_blocks)]


def _sequential(blocks, x):
    for blk in blocks:
        x = _block_fn(blk, x)
    return x


def test_stack_block_params():
    blocks = _make_blocks(4, 8)
    stacked = stack_block_params(blocks)
    assert stacked["w"].shape == (4, 8, 8)
    with pytest.raises(ValueError, match="identical parameter"):
        stack_block_params([{"w": jnp.zeros(2)}, {"x": jnp.zeros(2)}])


def test_pipeline_matches_sequential():
    m = dist.init_parallel_env(dp=2, pp=4)
    blocks = _make_blocks(8, 16)  # 2 blocks per stage
    stacked = stack_block_params(blocks)
    x = jnp.asarray(np.random.default_rng(1).normal(0, 1, (8, 16)), jnp.float32)
    ref = _sequential(blocks, x)

    stage = blockwise_stage_fn(_block_fn)

    def run(p, xs):
        return pipeline_apply(stage, p, xs, axis="pp")

    f = shard_map(run, mesh=m,
                  in_specs=({"w": PartitionSpec("pp"), "b": PartitionSpec("pp")},
                            PartitionSpec()),
                  out_specs=PartitionSpec(), check_rep=False)
    out = unmicrobatch(f(stacked, microbatch(x, 4)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_pipeline_gradients_match_sequential():
    m = dist.init_parallel_env(pp=4)
    blocks = _make_blocks(4, 8)
    stacked = stack_block_params(blocks)
    x = jnp.asarray(np.random.default_rng(2).normal(0, 1, (4, 8)), jnp.float32)

    def seq_loss(p):
        h = x
        for i in range(4):
            h = _block_fn({"w": p["w"][i], "b": p["b"][i]}, h)
        return jnp.sum(h ** 2)

    stage = blockwise_stage_fn(_block_fn)

    def pipe_loss(p):
        def run(pp_params, xs):
            return pipeline_apply(stage, pp_params, xs, axis="pp")

        f = shard_map(run, mesh=m,
                      in_specs=({"w": PartitionSpec("pp"), "b": PartitionSpec("pp")},
                                PartitionSpec()),
                      out_specs=PartitionSpec(), check_rep=False)
        out = unmicrobatch(f(p, microbatch(x, 2)))
        return jnp.sum(out ** 2)

    g_ref = jax.grad(seq_loss)(stacked)
    g_pipe = jax.grad(pipe_loss)(stacked)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_stage_wrapper():
    m = dist.init_parallel_env(pp=4)
    blocks = _make_blocks(4, 8)
    pipe = PipelineStage(_block_fn, stack_block_params(blocks), num_micro=2)
    pipe.shard_params()
    x = jnp.asarray(np.random.default_rng(3).normal(0, 1, (4, 8)), jnp.float32)
    out = pipe(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(blocks, x)),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_stage_degenerate_single_stage():
    dist.init_parallel_env(dp=8)  # no pp axis -> plain scan
    blocks = _make_blocks(3, 8)
    pipe = PipelineStage(_block_fn, stack_block_params(blocks), num_micro=2)
    x = jnp.ones((4, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(pipe(x)),
                               np.asarray(_sequential(blocks, x)),
                               rtol=2e-5, atol=2e-5)


def test_microbatch_roundtrip_and_errors():
    x = jnp.arange(24.0).reshape(6, 4)
    mb = microbatch(x, 3)
    assert mb.shape == (3, 2, 4)
    np.testing.assert_allclose(np.asarray(unmicrobatch(mb)), np.asarray(x))
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(x, 4)
