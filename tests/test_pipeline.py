"""Pipeline parallelism: circular ppermute schedule == sequential execution,
and gradients flow through the pipeline (SURVEY.md §2.2 "Pipeline
parallelism" — ref PipelineOptimizer fluid/optimizer.py:3661)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.collective import shard_map
from paddle_tpu.parallel.pipeline import (
    PipelineStage,
    blockwise_stage_fn,
    microbatch,
    pipeline_apply,
    stack_block_params,
    unmicrobatch,
)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_mod.set_mesh(None)


def _block_fn(blk, x):
    return jnp.tanh(x @ blk["w"] + blk["b"])


def _make_blocks(n_blocks, d, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rng.normal(0, 0.5, (d, d)), jnp.float32),
             "b": jnp.asarray(rng.normal(0, 0.1, (d,)), jnp.float32)}
            for _ in range(n_blocks)]


def _sequential(blocks, x):
    for blk in blocks:
        x = _block_fn(blk, x)
    return x


def test_stack_block_params():
    blocks = _make_blocks(4, 8)
    stacked = stack_block_params(blocks)
    assert stacked["w"].shape == (4, 8, 8)
    with pytest.raises(ValueError, match="identical parameter"):
        stack_block_params([{"w": jnp.zeros(2)}, {"x": jnp.zeros(2)}])


def test_pipeline_matches_sequential():
    m = dist.init_parallel_env(dp=2, pp=4)
    blocks = _make_blocks(8, 16)  # 2 blocks per stage
    stacked = stack_block_params(blocks)
    x = jnp.asarray(np.random.default_rng(1).normal(0, 1, (8, 16)), jnp.float32)
    ref = _sequential(blocks, x)

    stage = blockwise_stage_fn(_block_fn)

    def run(p, xs):
        return pipeline_apply(stage, p, xs, axis="pp")

    f = shard_map(run, mesh=m,
                  in_specs=({"w": PartitionSpec("pp"), "b": PartitionSpec("pp")},
                            PartitionSpec()),
                  out_specs=PartitionSpec(), check_rep=False)
    out = unmicrobatch(f(stacked, microbatch(x, 4)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_pipeline_gradients_match_sequential():
    m = dist.init_parallel_env(pp=4)
    blocks = _make_blocks(4, 8)
    stacked = stack_block_params(blocks)
    x = jnp.asarray(np.random.default_rng(2).normal(0, 1, (4, 8)), jnp.float32)

    def seq_loss(p):
        h = x
        for i in range(4):
            h = _block_fn({"w": p["w"][i], "b": p["b"][i]}, h)
        return jnp.sum(h ** 2)

    stage = blockwise_stage_fn(_block_fn)

    def pipe_loss(p):
        def run(pp_params, xs):
            return pipeline_apply(stage, pp_params, xs, axis="pp")

        f = shard_map(run, mesh=m,
                      in_specs=({"w": PartitionSpec("pp"), "b": PartitionSpec("pp")},
                                PartitionSpec()),
                      out_specs=PartitionSpec(), check_rep=False)
        out = unmicrobatch(f(p, microbatch(x, 2)))
        return jnp.sum(out ** 2)

    g_ref = jax.grad(seq_loss)(stacked)
    g_pipe = jax.grad(pipe_loss)(stacked)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_pipe[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_stage_wrapper():
    m = dist.init_parallel_env(pp=4)
    blocks = _make_blocks(4, 8)
    pipe = PipelineStage(_block_fn, stack_block_params(blocks), num_micro=2)
    pipe.shard_params()
    x = jnp.asarray(np.random.default_rng(3).normal(0, 1, (4, 8)), jnp.float32)
    out = pipe(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(blocks, x)),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_stage_degenerate_single_stage():
    dist.init_parallel_env(dp=8)  # no pp axis -> plain scan
    blocks = _make_blocks(3, 8)
    pipe = PipelineStage(_block_fn, stack_block_params(blocks), num_micro=2)
    x = jnp.ones((4, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(pipe(x)),
                               np.asarray(_sequential(blocks, x)),
                               rtol=2e-5, atol=2e-5)


def test_microbatch_roundtrip_and_errors():
    x = jnp.arange(24.0).reshape(6, 4)
    mb = microbatch(x, 3)
    assert mb.shape == (3, 2, 4)
    np.testing.assert_allclose(np.asarray(unmicrobatch(mb)), np.asarray(x))
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(x, 4)


# -- 1F1B -------------------------------------------------------------------

def _head_loss(hp, y, tgt, micro_idx=0):
    """Per-micro-batch loss: linear head + MSE (mean over the micro-batch)."""
    pred = y @ hp["w_out"]
    return jnp.mean((pred - tgt) ** 2)


def _run_1f1b(m, stacked, head, x, tgts, num_micro):
    from paddle_tpu.parallel.pipeline import pipeline_train_1f1b

    base = blockwise_stage_fn(_block_fn)
    stage = lambda p, x_, b: base(p, x_)

    def run(pp_params, hp, xs, ts):
        return pipeline_train_1f1b(stage, _head_loss, pp_params, hp, xs, ts,
                                   axis="pp")

    pspec = {"w": PartitionSpec("pp"), "b": PartitionSpec("pp")}
    f = shard_map(run, mesh=m,
                  in_specs=(pspec, PartitionSpec(), PartitionSpec(),
                            PartitionSpec()),
                  out_specs=(PartitionSpec(), pspec, PartitionSpec(),
                             PartitionSpec()),
                  check_rep=False)
    return f(stacked, head, microbatch(x, num_micro),
             microbatch(tgts, num_micro))


def _ref_loss_and_grads(stacked, head, x, tgts, num_micro):
    def total(p, hp, xs_in):
        def per_micro(xm, tm):
            h = xm
            for i in range(stacked["w"].shape[0]):
                h = _block_fn({"w": p["w"][i], "b": p["b"][i]}, h)
            return _head_loss(hp, h, tm)
        xs = microbatch(xs_in, num_micro)
        ts = microbatch(tgts, num_micro)
        losses = jax.vmap(per_micro)(xs, ts)
        return jnp.mean(losses)

    l, grads = jax.value_and_grad(total, argnums=(0, 1))(stacked, head, x)
    dxs = jax.grad(total, argnums=2)(stacked, head, x)
    return l, grads[0], grads[1], dxs


def test_pipeline_1f1b_matches_reference_loss_and_grads():
    m = dist.init_parallel_env(pp=4)
    rng = np.random.default_rng(4)
    blocks = _make_blocks(4, 8, seed=4)
    stacked = stack_block_params(blocks)
    head = {"w_out": jnp.asarray(rng.normal(0, 0.5, (8, 3)), jnp.float32)}
    num_micro, mb = 8, 2
    x = jnp.asarray(rng.normal(0, 1, (num_micro * mb, 8)), jnp.float32)
    tgts = jnp.asarray(rng.normal(0, 1, (num_micro * mb, 3)), jnp.float32)

    loss, sg, hg, dxs = _run_1f1b(m, stacked, head, x, tgts, num_micro)
    ref_l, ref_sg, ref_hg, ref_dx = _ref_loss_and_grads(
        stacked, head, x, tgts, num_micro)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    for k in ref_sg:
        np.testing.assert_allclose(np.asarray(sg[k]), np.asarray(ref_sg[k]),
                                   rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg["w_out"]),
                               np.asarray(ref_hg["w_out"]),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(unmicrobatch(dxs)),
                               np.asarray(ref_dx), rtol=2e-4, atol=1e-5)


def test_pipeline_1f1b_peak_memory_below_gpipe():
    """The 1F1B property: stashed state is O(n_stages), not O(num_micro).
    Compare XLA's temp-buffer sizing for many micro-batches."""
    from paddle_tpu.parallel.pipeline import pipeline_train_1f1b

    m = dist.init_parallel_env(pp=4)
    rng = np.random.default_rng(5)
    d, num_micro, mb = 64, 32, 4
    blocks = _make_blocks(4, d, seed=5)
    stacked = stack_block_params(blocks)
    head = {"w_out": jnp.asarray(rng.normal(0, 0.5, (d, 3)), jnp.float32)}
    x = jnp.asarray(rng.normal(0, 1, (num_micro * mb, d)), jnp.float32)
    tgts = jnp.asarray(rng.normal(0, 1, (num_micro * mb, 3)), jnp.float32)
    base = blockwise_stage_fn(_block_fn)
    stage = lambda p, x_, b: base(p, x_)
    gstage = base
    pspec = {"w": PartitionSpec("pp"), "b": PartitionSpec("pp")}

    def run_1f1b(p, hp, xs, ts):
        return pipeline_train_1f1b(stage, _head_loss, p, hp, xs, ts,
                                   axis="pp")

    f1 = jax.jit(shard_map(run_1f1b, mesh=m,
                           in_specs=(pspec, PartitionSpec(), PartitionSpec(),
                                     PartitionSpec()),
                           out_specs=(PartitionSpec(), pspec, PartitionSpec(),
                                      PartitionSpec()),
                           check_rep=False))

    def gpipe_loss(p, hp, xs):
        def run(pp_params, xs_):
            return pipeline_apply(gstage, pp_params, xs_, axis="pp")

        g = shard_map(run, mesh=m, in_specs=(pspec, PartitionSpec()),
                      out_specs=PartitionSpec(), check_rep=False)
        ys = g(p, xs)
        pred = ys @ hp["w_out"]
        return jnp.mean((pred - microbatch(tgts, num_micro)) ** 2)

    f2 = jax.jit(jax.value_and_grad(gpipe_loss, argnums=(0, 1)))

    xs = microbatch(x, num_micro)
    ts = microbatch(tgts, num_micro)
    mem1 = f1.lower(stacked, head, xs, ts).compile().memory_analysis()
    mem2 = f2.lower(stacked, head, xs).compile().memory_analysis()
    t1 = mem1.temp_size_in_bytes
    t2 = mem2.temp_size_in_bytes
    assert t1 < t2, (t1, t2)
    # and it still computes the right loss
    loss, *_ = f1(stacked, head, xs, ts)
    ref, _ = f2(stacked, head, xs)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
