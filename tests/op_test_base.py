"""OpTest harness (ref python/paddle/fluid/tests/unittests/op_test.py:170 —
the backbone of the reference's ~500 per-op test files).

A subclass declares ``op_type``, numpy ``inputs``/``attrs``/``outputs``;
``check_output`` builds a single-op Program, runs it through the real static
Executor (scratch Scope, same path as training), and compares against the
declared outputs.  ``check_grad`` compares analytic gradients — produced by
``static.gradients`` on a mean-of-output loss, exactly like the reference —
against central finite differences computed by re-running the FORWARD-only
program with perturbed feeds (ref op_test.py:57 get_numeric_gradient,
delta≈5e-3).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

import paddle_tpu.static as static


def _as_list(value):
    return list(value) if isinstance(value, (list, tuple)) else [value]


class OpTest:
    op_type: str = ""
    inputs: Dict[str, np.ndarray] = {}
    attrs: Dict = {}
    outputs: Dict[str, np.ndarray] = {}

    # -- program construction ------------------------------------------------

    def _build(self, grad_of: Tuple[str, Sequence[str]] = None):
        """Build the single-op program.  With ``grad_of=(output_slot,
        input_slots)`` also appends loss = mean(output) and its gradients
        w.r.t. every array of each listed input slot.  Returns
        (main, startup, out_fetches, loss_var, grad_fetches)."""
        from paddle_tpu.static import layers as L

        main, startup = static.Program(), static.Program()
        loss = None
        grad_fetches: List = []
        with static.program_guard(main, startup):
            block = main.current_block()
            in_names: Dict[str, List[str]] = {}
            in_vars: Dict[str, List] = {}
            for slot, value in self.inputs.items():
                names, varlist = [], []
                for i, arr in enumerate(_as_list(value)):
                    name = f"{slot.lower()}_{i}"
                    v = block.create_var(name=name, shape=tuple(arr.shape),
                                         dtype=str(arr.dtype), is_data=True,
                                         stop_gradient=False)
                    names.append(name)
                    varlist.append(v)
                in_names[slot] = names
                in_vars[slot] = varlist
            out_names: Dict[str, List[str]] = {}
            out_vars: Dict[str, List] = {}
            for slot, value in self.outputs.items():
                names, varlist = [], []
                for i, arr in enumerate(_as_list(value)):
                    name = f"out_{slot.lower()}_{i}"
                    v = block.create_var(name=name,
                                         shape=tuple(np.asarray(arr).shape),
                                         dtype=str(np.asarray(arr).dtype))
                    names.append(name)
                    varlist.append(v)
                out_names[slot] = names
                out_vars[slot] = varlist
            block.append_op(self.op_type, inputs=in_names,
                            outputs=out_names, attrs=dict(self.attrs))
            if grad_of is not None:
                output_slot, input_slots = grad_of
                loss = L.mean(out_vars[output_slot][0])
                wrt = [v for slot in input_slots for v in in_vars[slot]]
                grad_fetches = list(static.gradients([loss], wrt))
        out_fetches = [n for names in out_names.values() for n in names]
        return main, startup, out_fetches, loss, grad_fetches

    def _feed(self):
        """Fresh contiguous copies every call: the numeric sweep perturbs
        the fed arrays in place and must never mutate self.inputs (or be
        defeated by a non-contiguous view whose reshape(-1) is a copy)."""
        feed = {}
        for slot, value in self.inputs.items():
            for i, arr in enumerate(_as_list(value)):
                feed[f"{slot.lower()}_{i}"] = np.ascontiguousarray(arr)
        return feed

    # -- checks --------------------------------------------------------------

    def check_output(self, atol=1e-5, rtol=1e-5):
        main, startup, out_fetches, _, _ = self._build()
        exe = static.Executor()
        exe.run(startup)
        got = exe.run(main, feed=self._feed(), fetch_list=out_fetches)
        i = 0
        for slot, value in self.outputs.items():
            for expected in _as_list(value):
                np.testing.assert_allclose(
                    got[i], expected, atol=atol, rtol=rtol,
                    err_msg=f"{self.op_type} output {slot}")
                i += 1

    def check_grad(self, inputs_to_check: Sequence[str], output_name: str,
                   numeric_delta: float = 5e-3,
                   max_relative_error: float = 5e-3):
        """Analytic (static.gradients, ref backward.py:1215) vs central
        finite differences on loss = mean(output).  Checks EVERY array of
        each listed input slot; the numeric sweep runs the forward-only
        program (the backward subgraph would double every probe's cost)."""
        from paddle_tpu.static import layers as L

        g_main, g_startup, _, _, grad_fetches = self._build(
            grad_of=(output_name, inputs_to_check))
        exe = static.Executor()
        exe.run(g_startup)
        feed = self._feed()
        analytic = exe.run(g_main, feed=feed, fetch_list=grad_fetches)

        # forward-only program for the numeric probes
        f_main, f_startup, _, f_loss, _ = self._build(
            grad_of=(output_name, ()))
        exe.run(f_startup)

        idx = 0
        for slot in inputs_to_check:
            for i, _ in enumerate(_as_list(self.inputs[slot])):
                a_grad = np.asarray(analytic[idx])
                idx += 1
                arr = feed[f"{slot.lower()}_{i}"]
                numeric = np.zeros(arr.shape, np.float64)
                flat = arr.reshape(-1)          # in-place view (contiguous)
                nflat = numeric.reshape(-1)
                for j in range(flat.size):
                    orig = flat[j]
                    for sign in (+1, -1):
                        flat[j] = orig + sign * numeric_delta
                        out, = exe.run(f_main, feed=feed,
                                       fetch_list=[f_loss])
                        nflat[j] += sign * float(out)
                    flat[j] = orig
                numeric /= (2 * numeric_delta)
                denom = np.maximum(np.abs(numeric), 1e-3)
                rel = np.abs(a_grad - numeric) / denom
                assert rel.max() <= max_relative_error, (
                    f"{self.op_type} grad w.r.t. {slot}[{i}]: max rel err "
                    f"{rel.max():.2e} > {max_relative_error:.0e}")
