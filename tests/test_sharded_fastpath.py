"""Sharded steady-state fast path + persistent AOT executable cache.

Covers the PR-6 contract:
  * the donated SHARDED step is bitwise-identical to the undonated sharded
    step (donation never changes math), and matches the single-device fast
    path at the DP tolerance — different XLA executables (GSPMD partitioned
    vs single-device) legitimately differ in ulps, so cross-executable
    parity is tolerance-based, never bitwise;
  * steady state under a sharding plan compiles exactly once (cache_miss
    == 1) and never re-traces Python (`executor.traces` stops growing);
  * the persistent executable cache round-trips: compile -> store -> fresh
    Executor deserializes (compile_cache_hit) with bitwise-identical
    losses; eviction recompiles; a corrupted file falls back cleanly; and
    a SECOND PROCESS warm-starts without a single Python trace.

Cache tests share ONE Program object across runs inside a process: the
global unique-name counter makes a rebuilt program fingerprint-different
(fresh processes regenerate identical names, which the subprocess test
exercises for real).
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.core import flags
from paddle_tpu.parallel.mesh import DP_AXIS
from paddle_tpu.parallel.sharding import ShardingPlan
from paddle_tpu.static import compile_cache as cc
from paddle_tpu.static import executor as executor_mod
from paddle_tpu.static import layers as L
from paddle_tpu.utils import monitor

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU mesh")


@pytest.fixture
def _flags_guard():
    saved = flags.get_flags(["donate_state", "metrics", "compile_cache_dir"])
    yield
    flags.set_flags(saved)


def _mesh(n: int) -> Mesh:
    return Mesh(np.asarray(jax.devices()[:n]), (DP_AXIS,))


def _build_net(seed: int = 7):
    main, startup = static.Program(), static.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with static.program_guard(main, startup):
        x = L.data("x", [8])
        y = L.data("y", [1])
        pred = L.fc(L.fc(x, 16, act="relu"), 1)
        loss = L.mean(L.square(L.elementwise_sub(pred, y)))
        static.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _feed(batch: int = 16):
    rng = np.random.default_rng(3)
    return {"x": rng.normal(size=(batch, 8)).astype(np.float32),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}


def _train(run_target, main, startup, loss, steps: int = 5,
           return_numpy: bool = False):
    """Fresh Scope+Executor over an already-built program; float losses."""
    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        out = [exe.run(run_target, feed=feed, fetch_list=[loss],
                       return_numpy=return_numpy)[0] for _ in range(steps)]
        return [float(np.asarray(l)) for l in out], scope


# ---------------------------------------------------------------------------
# sharded fast-path parity
# ---------------------------------------------------------------------------

@needs_devices
def test_sharded_donated_matches_undonated_bitwise(_flags_guard, monkeypatch):
    """Donation must not change math: the same sharded plan with and
    without state donation yields bit-for-bit identical losses (CPU skips
    donation by default, so force it through the platform gate)."""
    monkeypatch.setattr(executor_mod, "_FORCE_DONATION", True)
    flags.set_flags({"donate_state": True})
    mesh = _mesh(8)

    main, startup, loss = _build_net(seed=7)
    donated = static.CompiledProgram(main).with_sharding(mesh=mesh,
                                                         donate=True)
    d_losses, _ = _train(donated, main, startup, loss)

    main2, startup2, loss2 = _build_net(seed=7)
    undonated = static.CompiledProgram(main2).with_sharding(mesh=mesh,
                                                            donate=False)
    u_losses, _ = _train(undonated, main2, startup2, loss2)

    assert d_losses == u_losses  # bitwise: same plan, same executable math


@needs_devices
def test_sharded_matches_unsharded_within_tolerance(_flags_guard):
    """8-device GSPMD partitioning reorders the batch reduction (psum tree
    vs flat sum), so sharded-vs-single-device parity is ulp-level, not
    bitwise — the same rel=2e-4 contract test_static_dp.py pins for
    with_data_parallel."""
    flags.set_flags({"donate_state": True})

    main, startup, loss = _build_net(seed=7)
    base, _ = _train(main, main, startup, loss)

    main2, startup2, loss2 = _build_net(seed=7)
    sharded = static.CompiledProgram(main2).with_sharding(mesh=_mesh(8))
    got, _ = _train(sharded, main2, startup2, loss2)

    assert got == pytest.approx(base, rel=2e-4)


@needs_devices
def test_sharded_zero_steady_state_retraces(_flags_guard):
    """Under a sharding plan the hot cache must hold: one compile on the
    first step, every later step a hit, and the Python tracer never runs
    again (`executor.traces` counts trace-time host effects)."""
    flags.set_flags({"donate_state": True, "metrics": True})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(mesh=_mesh(8))

    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        miss0 = reg.get("executor.cache_miss").value()
        hit0 = reg.get("executor.cache_hit").value()
        exe.run(compiled, feed=feed, fetch_list=[loss], return_numpy=False)
        traces1 = reg.get("executor.traces").value()
        n = 6
        for _ in range(n - 1):
            exe.run(compiled, feed=feed, fetch_list=[loss],
                    return_numpy=False)
        assert reg.get("executor.cache_miss").value() - miss0 == 1
        assert reg.get("executor.cache_hit").value() - hit0 == n - 1
        # zero retraces after the first step
        assert reg.get("executor.traces").value() == traces1


@needs_devices
def test_sharded_state_and_fetches_live_on_the_mesh(_flags_guard):
    """After sharded steps the persistable state written back to the scope
    is device-resident across the whole mesh (replicated NamedSharding
    under the default plan) — no per-step host round-trip."""
    flags.set_flags({"donate_state": True})
    mesh = _mesh(8)
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(mesh=mesh)

    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        out = None
        for _ in range(3):
            out = exe.run(compiled, feed=feed, fetch_list=[loss],
                          return_numpy=False)[0]
        assert isinstance(out, jax.Array)
        persistables = [v.name for v in main.global_block().vars.values()
                        if getattr(v, "persistable", False)]
        assert persistables
        repl = NamedSharding(mesh, P())
        on_mesh = 0
        for name in persistables:
            val = scope.find_var(name)
            if not isinstance(val, jax.Array):
                continue
            assert val.sharding.is_equivalent_to(repl, val.ndim), name
            assert len(val.sharding.device_set) == 8, name
            on_mesh += 1
        assert on_mesh >= 2  # at minimum the two fc weight/bias pairs


@needs_devices
def test_sharded_indivisible_batch_raises(_flags_guard):
    flags.set_flags({"donate_state": True})
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(mesh=_mesh(8))
    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.default_rng(3)
        bad = {"x": rng.normal(size=(12, 8)).astype(np.float32),
               "y": rng.normal(size=(12, 1)).astype(np.float32)}
        with pytest.raises(ValueError, match="does not divide"):
            exe.run(compiled, feed=bad, fetch_list=[loss],
                    return_numpy=False)


@needs_devices
def test_device_feeder_stages_plan_shardings():
    """DeviceFeeder(device=plan.feed_shardings(...)) hands the consumer
    batches whose leaves already carry the plan's NamedShardings — the
    Executor's placement rim then passes them through by identity."""
    from paddle_tpu.io import DeviceFeeder

    mesh = _mesh(4)
    plan = ShardingPlan(mesh=mesh, donate=False)
    batch = _feed()
    shardings = plan.feed_shardings(batch, mesh)
    feeder = DeviceFeeder([batch, batch], device=shardings)
    staged = list(feeder)
    assert len(staged) == 2
    for got in staged:
        for k, v in got.items():
            assert isinstance(v, jax.Array)
            assert v.sharding.is_equivalent_to(shardings[k], v.ndim), k


# ---------------------------------------------------------------------------
# persistent executable cache
# ---------------------------------------------------------------------------

def _cc_counters(reg):
    def val(name):
        m = reg.get(name)
        return m.value() if m is not None else 0
    return (val("executor.compile_cache_hit"),
            val("executor.compile_cache_miss"),
            val("executor.traces"))


def test_compile_cache_roundtrip_evict_reload(_flags_guard, tmp_path):
    """compile -> store -> fresh Executor reloads from disk (hit, zero
    traces) with bitwise-identical fetches; evicting the files recompiles
    (miss) to the same numbers."""
    flags.set_flags({"donate_state": True, "metrics": True,
                     "compile_cache_dir": str(tmp_path)})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)

    cold, _ = _train(main, main, startup, loss)
    files = sorted(tmp_path.glob("*.pdtc"))
    assert files, "cold run stored no executables"

    h0, m0, t0 = _cc_counters(reg)
    warm, _ = _train(main, main, startup, loss)
    h1, m1, t1 = _cc_counters(reg)
    assert warm == cold                      # bitwise: same executable bytes
    assert h1 - h0 >= 2                      # startup + main both reloaded
    assert m1 - m0 == 0
    assert t1 - t0 == 0                      # deserialization never re-traces

    for f in files:
        f.unlink()
    h0, m0, _ = _cc_counters(reg)
    again, _ = _train(main, main, startup, loss)
    h1, m1, _ = _cc_counters(reg)
    assert again == cold
    assert h1 - h0 == 0 and m1 - m0 >= 2     # evicted -> recompiled+stored
    assert sorted(tmp_path.glob("*.pdtc"))   # ...and stored again


def test_compile_cache_corrupted_file_falls_back(_flags_guard, tmp_path):
    """A truncated/bit-flipped cache file must recompile cleanly (digest
    check), never crash or load garbage."""
    flags.set_flags({"donate_state": True, "metrics": True,
                     "compile_cache_dir": str(tmp_path)})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)
    cold, _ = _train(main, main, startup, loss)

    for f in tmp_path.glob("*.pdtc"):
        blob = bytearray(f.read_bytes())
        blob[60:64] = b"\xde\xad\xbe\xef"    # inside the payload
        f.write_bytes(bytes(blob))

    h0, _, _ = _cc_counters(reg)
    got, _ = _train(main, main, startup, loss)
    h1, _, _ = _cc_counters(reg)
    assert got == cold
    assert h1 - h0 == 0                      # corrupt files never count as hits


def test_compile_cache_mismatched_key_misses(_flags_guard, tmp_path):
    """The key covers fetches and feed signatures: changing either must
    miss rather than replay the wrong executable."""
    flags.set_flags({"donate_state": True, "metrics": True,
                     "compile_cache_dir": str(tmp_path)})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)
    _train(main, main, startup, loss, steps=1)

    scope = static.Scope()
    h0, m0, _ = _cc_counters(reg)
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)                     # hits
        exe.run(main, feed=_feed(batch=32), fetch_list=[loss],
                return_numpy=False)          # new feed shape -> miss
    h1, m1, _ = _cc_counters(reg)
    assert m1 - m0 == 1
    assert h1 - h0 == 1


def test_build_cache_key_sensitivity():
    """Unit check on the key: program contents, fetches, donation, and the
    sharding-plan fingerprint all feed the digest."""
    main, _, loss = _build_net(seed=7)
    feeds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in _feed().items()}

    def key(**kw):
        a = dict(program=main, seed=7, fetch_names=(loss.name,),
                 feed_arrays=feeds, donated={}, carried={}, donate=False,
                 plan_fingerprint=None)
        a.update(kw)
        return cc.build_cache_key(**a)

    base = key()
    assert key() == base
    assert key(fetch_names=()) != base
    assert key(donate=True) != base
    assert key(seed=8) != base
    assert key(plan_fingerprint="mesh(dp=8)x8@cpu:cpu|...") != base

    main2, _, _ = _build_net(seed=7)  # fresh names -> different fingerprint
    assert (cc.program_fingerprint(main2) != cc.program_fingerprint(main))


_CHILD = r"""
import json, sys
import numpy as np
import paddle_tpu.static as static
from paddle_tpu.core import flags
from paddle_tpu.static import layers as L
from paddle_tpu.utils import monitor

flags.set_flags({"donate_state": True, "metrics": True,
                 "compile_cache_dir": sys.argv[1]})
main, startup = static.Program(), static.Program()
main.random_seed = 7
startup.random_seed = 7
with static.program_guard(main, startup):
    x = L.data("x", [8])
    y = L.data("y", [1])
    pred = L.fc(L.fc(x, 16, act="relu"), 1)
    loss = L.mean(L.square(L.elementwise_sub(pred, y)))
    static.optimizer.SGD(learning_rate=0.05).minimize(loss)
scope = static.Scope()
with static.scope_guard(scope):
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.default_rng(3)
    feed = {"x": rng.normal(size=(16, 8)).astype(np.float32),
            "y": rng.normal(size=(16, 1)).astype(np.float32)}
    losses = [float(np.asarray(
        exe.run(main, feed=feed, fetch_list=[loss])[0])) for _ in range(4)]
reg = monitor.default_registry()
def val(n):
    m = reg.get(n)
    return m.value() if m is not None else 0
print(json.dumps({"losses": losses,
                  "cc_hit": val("executor.compile_cache_hit"),
                  "cc_miss": val("executor.compile_cache_miss"),
                  "traces": val("executor.traces")}))
"""


def test_compile_cache_cross_process_warm_start(tmp_path):
    """The real contract: a SECOND PROCESS with a warm compile_cache_dir
    deserializes every executable — compile_cache_hit > 0 and zero Python
    traces — and reproduces the first process's losses bit-for-bit."""
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    cache = tmp_path / "cc"
    cache.mkdir()
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(repo) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))

    def run_once():
        proc = subprocess.run(
            [sys.executable, str(script), str(cache)], cwd=repo,
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold = run_once()
    assert cold["cc_miss"] >= 2 and cold["cc_hit"] == 0
    assert cold["traces"] >= 2

    warm = run_once()
    assert warm["losses"] == cold["losses"]   # bitwise across processes
    assert warm["cc_hit"] >= 2 and warm["cc_miss"] == 0
    assert warm["traces"] == 0                # tracing/lowering fully skipped


@needs_devices
def test_compile_cache_with_sharding_plan(_flags_guard, tmp_path):
    """Sharded executables cache too: the plan fingerprint is in the key,
    so a warm reload under the same mesh hits and stays parity-exact."""
    flags.set_flags({"donate_state": True, "metrics": True,
                     "compile_cache_dir": str(tmp_path)})
    reg = monitor.default_registry()
    mesh = _mesh(8)
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(mesh=mesh)

    cold, _ = _train(compiled, main, startup, loss)
    assert sorted(tmp_path.glob("*.pdtc"))
    h0, m0, t0 = _cc_counters(reg)
    warm, _ = _train(compiled, main, startup, loss)
    h1, m1, t1 = _cc_counters(reg)
    assert warm == cold
    assert h1 - h0 >= 1 and t1 - t0 == 0
