"""Quantized, bucketed, topology-aware gradient allreduce (parallel/compress.py).

Covers the PR-7 contract:
  * blockwise int8/fp8 quantization round-trips within the per-block error
    bound at every block size, and zero blocks round-trip exactly;
  * bucket assignment and the bucket signature are deterministic — the
    signature is byte-identical in a SECOND PROCESS;
  * the unquantized bucketed/hierarchical paths are parity-exact with
    lax.psum/pmean (bitwise on integer-valued data), and the quantized
    path lands within the blockwise error bound;
  * fleet's `DistributedStrategy.comm_quantize` gradient sync trains a toy
    problem to the same loss as the builder-owned pmean (exact for
    "none", tolerance-bounded for "int8"/"fp8");
  * dygraph `DataParallel(comm_buffer_size=...)` rides the same bucketer
    and rejects non-positive buffer sizes;
  * eager `collective.all_reduce` records comm.allreduce_bytes/_ms and
    comm.compress_ratio;
  * the Executor keeps zero steady-state retraces and a working persistent
    compile cache under `with_sharding(comm_quantize=...)` (the comm
    options ride the plan fingerprint into the cache key).
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu.distributed as dist
import paddle_tpu.static as static
from paddle_tpu.core import flags
from paddle_tpu.optimizer import SGD
from paddle_tpu.parallel import collective as coll
from paddle_tpu.parallel import compress
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.data_parallel import DataParallel
from paddle_tpu.parallel.fleet import DistributedOptimizer, DistributedStrategy
from paddle_tpu.parallel.mesh import DP_AXIS
from paddle_tpu.parallel.sharding import ShardingPlan
from paddle_tpu.static import layers as L
from paddle_tpu.utils import monitor

try:
    from jax import shard_map as _smap
except ImportError:  # pragma: no cover - older jax spelling
    from jax.experimental.shard_map import shard_map as _smap


needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU mesh")


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_mod.set_mesh(None)


@pytest.fixture
def _flags_guard():
    saved = flags.get_flags(["donate_state", "metrics", "compile_cache_dir"])
    yield
    flags.set_flags(saved)


def _mesh(n: int) -> Mesh:
    return Mesh(np.asarray(jax.devices()[:n]), (DP_AXIS,))


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)
    except TypeError:  # newer jax renamed the replication-check kwarg
        return _smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


# ---------------------------------------------------------------------------
# blockwise quantization round trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("block_size", [64, 256, 1024])
def test_int8_roundtrip_error_bound(block_size):
    """Per element the int8 error is at most half a quantization step:
    amax(block)/(2*127)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4 * block_size,)).astype(np.float32) * 3.0
    q, s = compress.quantize_blockwise(x, "int8", block_size)
    assert q.dtype == jnp.int8
    assert s.shape == (4,)
    back = np.asarray(compress.dequantize_blockwise(q, s, block_size))
    amax = np.abs(x.reshape(4, block_size)).max(axis=1, keepdims=True)
    bound = np.broadcast_to(amax / (2 * 127.0) + 1e-7,
                            (4, block_size)).reshape(-1)
    assert np.all(np.abs(back - x) <= bound)


@pytest.mark.parametrize("block_size", [64, 256])
def test_fp8_roundtrip_error_bound(block_size):
    if not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no fp8 dtype in this jaxlib")
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2 * block_size,)).astype(np.float32)
    q, s = compress.quantize_blockwise(x, "fp8", block_size)
    back = np.asarray(compress.dequantize_blockwise(q, s, block_size))
    # e4m3 keeps ~3 mantissa bits: relative error per element <~ 2^-3 / 2
    assert np.all(np.abs(back - x) <= np.abs(x) * 0.0725 + 1e-6)


def test_quantize_zero_block_exact():
    x = np.zeros((512,), np.float32)
    x[256:] = np.linspace(-1, 1, 256)
    q, s = compress.quantize_blockwise(x, "int8", 256)
    assert float(s[0]) == 0.0
    back = np.asarray(compress.dequantize_blockwise(q, s, 256))
    assert np.all(back[:256] == 0.0)


def test_quantize_rejects_ragged_input():
    with pytest.raises(ValueError, match="block_size"):
        compress.quantize_blockwise(np.ones((100,), np.float32), "int8", 256)
    with pytest.raises(ValueError, match="unknown compression kind"):
        compress.quantize_blockwise(np.ones((256,), np.float32), "int4", 256)


def test_wire_bytes_accounting():
    n, nelem = 8, 1 << 20
    raw = compress.wire_bytes(nelem, None, 256, n)
    q = compress.wire_bytes(nelem, "int8", 256, n)
    assert raw == int(2 * (n - 1) / n * nelem * 4)
    # the acceptance gate: quantized wire traffic <= 30% of fp32
    assert q / raw <= 0.30
    assert compress.wire_bytes(nelem, "int8", 256, 1) == 0


# ---------------------------------------------------------------------------
# bucketing determinism
# ---------------------------------------------------------------------------

def test_bucket_assignment_greedy_and_deterministic():
    cap_mb = 1024 / (1 << 20)  # a 1 KB cap expressed in MB
    sizes = [400, 400, 400, 2048, 100]
    b1 = compress.bucket_assignment(sizes, cap_mb)
    b2 = compress.bucket_assignment(list(sizes), cap_mb)
    assert b1 == b2
    assert b1 == [[0, 1], [2], [3], [4]]  # oversized leaf gets its own bucket


def _grad_tree():
    rng = np.random.default_rng(7)
    return {
        "fc1": {"w": jnp.asarray(rng.normal(size=(64, 32)), jnp.float32),
                "b": jnp.asarray(rng.normal(size=(32,)), jnp.float32)},
        "fc2": {"w": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32),
                "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32)},
    }


def test_bucket_signature_stable_in_process():
    g = _grad_tree()
    sig1 = compress.bucket_signature(g, 25.0)
    sig2 = compress.bucket_signature(_grad_tree(), 25.0)
    assert sig1 == sig2
    assert compress.bucket_signature(g, 1e-4) != sig1  # cap feeds the digest


_SIG_CHILD = r"""
import json
import jax.numpy as jnp
import numpy as np
from paddle_tpu.parallel import compress
rng = np.random.default_rng(7)
g = {
    "fc1": {"w": jnp.asarray(rng.normal(size=(64, 32)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(32,)), jnp.float32)},
    "fc2": {"w": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32)},
}
print(json.dumps({"sig": compress.bucket_signature(g, 25.0)}))
"""


def test_bucket_signature_cross_process(tmp_path):
    """The signature is safe for the persistent compile-cache key: a second
    process computes the identical digest."""
    script = tmp_path / "sig_child.py"
    script.write_text(_SIG_CHILD)
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(repo) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, str(script)], cwd=repo,
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    child_sig = json.loads(proc.stdout.strip().splitlines()[-1])["sig"]
    assert child_sig == compress.bucket_signature(_grad_tree(), 25.0)


# ---------------------------------------------------------------------------
# allreduce parity on the 8-device mesh
# ---------------------------------------------------------------------------

def _per_shard(seed, shape=(8, 1024)):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


@needs_devices
def test_bucketed_unquantized_matches_pmean():
    m = _mesh(8)
    xs = _per_shard(0)

    def both(x_local):
        x = x_local[0]
        g = {"a": x[:600], "b": x[600:].reshape(53, 8)}
        bucketed = compress.bucketed_all_reduce(
            g, DP_AXIS, buffer_mb=1e-3, hierarchy=None, mean=True)
        plain = jax.tree_util.tree_map(
            lambda v: jax.lax.pmean(v, DP_AXIS), g)
        return bucketed, plain

    with m:
        (bk, pl) = _shard_map(both, m, (P(DP_AXIS),), (P(), P()))(xs)
    for k in ("a", "b"):
        np.testing.assert_array_equal(np.asarray(bk[k]), np.asarray(pl[k]))


@needs_devices
def test_quantized_allreduce_error_bound():
    """int8 allreduce vs exact psum: relative L2 error stays small (each
    element is off by at most a quantization step of its block, twice)."""
    m = _mesh(8)
    xs = _per_shard(1)

    def both(x_local):
        x = x_local[0]
        exact = jax.lax.psum(x, DP_AXIS)
        q = compress.all_reduce_compressed(x, DP_AXIS, compress="int8",
                                           block_size=256)
        return exact, q

    with m:
        exact, q = _shard_map(both, m, (P(DP_AXIS),), (P(), P()))(xs)
    exact, q = np.asarray(exact), np.asarray(q)
    rel = np.linalg.norm(q - exact) / np.linalg.norm(exact)
    assert rel <= 0.05, rel


@needs_devices
def test_hierarchical_matches_flat_bitwise_on_integer_data():
    """On integer-valued fp32 data every partial sum is exact, so the
    hierarchical schedule (intra reduce-scatter -> inter allreduce -> intra
    all-gather) must equal flat psum bit-for-bit."""
    m = _mesh(8)
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.integers(-64, 64, size=(8, 4096)), jnp.float32)

    def both(x_local):
        x = x_local[0]
        flat = compress.optimized_all_reduce(x, DP_AXIS, hierarchy=None)
        hier = compress.optimized_all_reduce(x, DP_AXIS, hierarchy=2)
        return flat, hier

    with m:
        flat, hier = _shard_map(both, m, (P(DP_AXIS),), (P(), P()))(xs)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))


@needs_devices
def test_hierarchical_quantized_error_bound():
    m = _mesh(8)
    xs = _per_shard(4)

    def both(x_local):
        x = x_local[0]
        exact = jax.lax.psum(x, DP_AXIS)
        q = compress.optimized_all_reduce(x, DP_AXIS, compress="int8",
                                          hierarchy=2)
        return exact, q

    with m:
        exact, q = _shard_map(both, m, (P(DP_AXIS),), (P(), P()))(xs)
    exact, q = np.asarray(exact), np.asarray(q)
    rel = np.linalg.norm(q - exact) / np.linalg.norm(exact)
    assert rel <= 0.05, rel


def test_resolve_hierarchy_normalization():
    assert compress.resolve_hierarchy(None, 8) is None
    assert compress.resolve_hierarchy("off", 8) is None
    assert compress.resolve_hierarchy(2, 8) == (2, 4)
    assert compress.resolve_hierarchy((4, 2), 8) == (4, 2)
    assert compress.resolve_hierarchy(8, 8) is None  # degenerate: one group
    with pytest.raises(ValueError, match="does not divide"):
        compress.resolve_hierarchy(3, 8)
    with pytest.raises(ValueError, match="does not factor"):
        compress.resolve_hierarchy((3, 2), 8)


def test_hierarchical_groups_host_major():
    intra, inter = compress.hierarchical_groups(8, 4)
    assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert inter == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_dp_hierarchy_factors_by_local_devices():
    assert mesh_mod.dp_hierarchy(8, local=4) == (4, 2)
    assert mesh_mod.dp_hierarchy(8, local=8) is None   # single host
    assert mesh_mod.dp_hierarchy(8, local=1) is None   # one device per host
    assert mesh_mod.dp_hierarchy(8, local=3) is None   # does not divide


# ---------------------------------------------------------------------------
# collective.all_reduce front door
# ---------------------------------------------------------------------------

@needs_devices
def test_all_reduce_compress_traced():
    m = dist.init_parallel_env(dp=8)
    xs = _per_shard(5, (8, 512))

    def f(x_local):
        x = x_local[0]
        return coll.all_reduce(x, compress="int8"), jax.lax.psum(x, DP_AXIS)

    with m:
        q, exact = _shard_map(f, m, (P(DP_AXIS),), (P(), P()))(xs)
    rel = (np.linalg.norm(np.asarray(q) - np.asarray(exact))
           / np.linalg.norm(np.asarray(exact)))
    assert rel <= 0.05, rel


@needs_devices
def test_all_reduce_compress_scope_inherited():
    """compress=None inherits the ambient comm_scope; "none" opts out."""
    m = dist.init_parallel_env(dp=8)
    xs = _per_shard(6, (8, 512))
    opts = compress.CommOptions(quantize="int8", hierarchy=None)

    def f(x_local):
        x = x_local[0]
        with compress.comm_scope(opts):
            ambient = coll.all_reduce(x)            # quantized via scope
            exact = coll.all_reduce(x, compress="none")  # forced exact
        return ambient, exact, jax.lax.psum(x, DP_AXIS)

    with m:
        ambient, exact, psum = _shard_map(
            f, m, (P(DP_AXIS),), (P(), P(), P()))(xs)
    np.testing.assert_array_equal(np.asarray(exact), np.asarray(psum))
    assert not np.array_equal(np.asarray(ambient), np.asarray(psum))
    rel = (np.linalg.norm(np.asarray(ambient) - np.asarray(psum))
           / np.linalg.norm(np.asarray(psum)))
    assert rel <= 0.05


def test_all_reduce_rejects_bad_compress():
    with pytest.raises(ValueError, match="compress="):
        coll.all_reduce(jnp.ones((4,)), compress="int4")


@needs_devices
def test_eager_all_reduce_records_metrics(_flags_guard):
    flags.set_flags({"metrics": True})
    reg = monitor.default_registry()
    dist.init_parallel_env(dp=8)
    x = jnp.asarray(np.arange(512, dtype=np.float32))

    def _snap():
        by_ = reg.get("comm.allreduce_bytes")
        if by_ is None:
            return 0, 0
        return (by_.count(axis=DP_AXIS, dtype="int8"),
                by_.sum(axis=DP_AXIS, dtype="int8"))

    c0, s0 = _snap()
    out = coll.all_reduce(x)                      # fp32 eager
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8, rtol=1e-6)
    qout = coll.all_reduce(x, compress="int8")    # quantized eager
    rel = (np.linalg.norm(np.asarray(qout) - np.asarray(x) * 8)
           / max(np.linalg.norm(np.asarray(x) * 8), 1e-9))
    assert rel <= 0.05

    by = reg.get("comm.allreduce_bytes")
    ms = reg.get("comm.allreduce_ms")
    ratio = reg.get("comm.compress_ratio")
    assert by is not None and ms is not None and ratio is not None
    assert by.count(axis=DP_AXIS, dtype="float32") >= 1
    c1, s1 = _snap()
    wire = compress.wire_bytes(512, "int8", 256, 8)
    assert c1 - c0 >= 1                 # the eager quantized call landed
    assert (s1 - s0) >= wire and (s1 - s0) % wire == 0
    assert ms.count(axis=DP_AXIS) >= 2
    assert 0 < ratio.value() <= 0.30


# ---------------------------------------------------------------------------
# fleet comm_quantize end-to-end
# ---------------------------------------------------------------------------

def _toy_problem(seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(8, 1).astype(np.float32)
    xs = rng.randn(64, 8).astype(np.float32)
    ys = xs @ w_true
    return jnp.asarray(xs), jnp.asarray(ys)


def _fleet_train(comm_quantize: str, steps: int = 15):
    """Toy dp=8 regression; comm_quantize="" means builder-owned pmean."""
    m = dist.init_parallel_env(dp=8)
    strategy = DistributedStrategy()
    strategy.comm_quantize = comm_quantize
    strategy.comm_configs.hierarchical = None
    opt = DistributedOptimizer(SGD(0.05), strategy)
    xs, ys = _toy_problem()
    params = {"w": jnp.zeros((8, 1), jnp.float32)}
    state = opt.init(params)

    def step(x_l, y_l, p, s):
        def loss_fn(p_):
            return jnp.mean((x_l @ p_["w"] - y_l) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        if not comm_quantize:  # legacy contract: the builder syncs
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
        p2, s2 = opt.update(grads, s, p)
        return jax.lax.pmean(loss, "dp"), p2, s2

    losses = []
    with m:
        f = _shard_map(step, m, (P("dp"), P("dp"), P(), P()),
                       (P(), P(), P()))
        for _ in range(steps):
            loss, params, state = f(xs, ys, params, state)
            losses.append(float(loss))
    return losses


@needs_devices
def test_fleet_owned_sync_matches_builder_sync():
    base = _fleet_train("")
    owned = _fleet_train("none")
    assert owned == pytest.approx(base, rel=1e-5, abs=1e-7)


@needs_devices
@pytest.mark.parametrize("kind", ["int8", "fp8"])
def test_fleet_quantized_training_converges(kind):
    if kind == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no fp8 dtype in this jaxlib")
    base = _fleet_train("")
    q = _fleet_train(kind)
    assert q[-1] < 0.1 * q[0]                    # it actually trains
    assert abs(q[-1] - base[-1]) <= 0.05         # and lands near the exact run


def test_fleet_rejects_unknown_comm_quantize():
    strategy = DistributedStrategy()
    strategy.comm_quantize = "int4"
    with pytest.raises(ValueError, match="comm_quantize"):
        DistributedOptimizer(SGD(0.05), strategy)


# ---------------------------------------------------------------------------
# dygraph DataParallel face
# ---------------------------------------------------------------------------

def test_data_parallel_rejects_nonpositive_buffer():
    from paddle_tpu.nn import Linear
    with pytest.raises(ValueError, match="comm_buffer_size"):
        DataParallel(Linear(4, 4), comm_buffer_size=0)
    with pytest.raises(ValueError, match="comm_buffer_size"):
        DataParallel(Linear(4, 4), comm_buffer_size=-3)
    with pytest.raises(ValueError, match="comm_buffer_size"):
        DataParallel(Linear(4, 4), comm_buffer_size=None)


@needs_devices
def test_data_parallel_bucketed_grads_match_pmean():
    from paddle_tpu.distributed import env as dist_env

    m = dist.init_parallel_env(dp=8)
    from paddle_tpu.nn import Linear
    model = DataParallel(Linear(4, 4), comm_buffer_size=25)
    xs = _per_shard(9, (8, 256))

    def f(x_local):
        x = x_local[0]
        g = {"w": x.reshape(16, 16), "b": x[:16]}
        with dist_env.data_axis_scope(DP_AXIS):
            synced = model.apply_collective_grads(g)
        ref = jax.tree_util.tree_map(lambda v: jax.lax.pmean(v, DP_AXIS), g)
        return synced, ref

    with m:
        got, ref = _shard_map(f, m, (P(DP_AXIS),), (P(), P()))(xs)
    for k in ("w", "b"):
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]))


# ---------------------------------------------------------------------------
# executor: zero retraces + compile cache under comm options
# ---------------------------------------------------------------------------

def _build_net(seed: int = 7):
    main, startup = static.Program(), static.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with static.program_guard(main, startup):
        x = L.data("x", [8])
        y = L.data("y", [1])
        pred = L.fc(L.fc(x, 16, act="relu"), 1)
        loss = L.mean(L.square(L.elementwise_sub(pred, y)))
        static.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _feed(batch: int = 16):
    rng = np.random.default_rng(3)
    return {"x": rng.normal(size=(batch, 8)).astype(np.float32),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}


def _train(run_target, main, startup, loss, steps: int = 5):
    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        out = [exe.run(run_target, feed=feed, fetch_list=[loss],
                       return_numpy=False)[0] for _ in range(steps)]
        return [float(np.asarray(l)) for l in out], scope


def test_plan_fingerprint_carries_comm_options():
    m = _mesh(min(8, jax.device_count()))
    base = ShardingPlan(mesh=m).fingerprint()
    quant = ShardingPlan(mesh=m, comm_quantize="int8").fingerprint()
    quant2 = ShardingPlan(mesh=m, comm_quantize="int8",
                          comm_buffer_mb=4.0).fingerprint()
    assert base != quant
    assert quant != quant2
    assert ShardingPlan(mesh=m, comm_quantize="int8").fingerprint() == quant


@needs_devices
def test_sharded_zero_retraces_under_comm_quantize(_flags_guard):
    """Acceptance: comm_quantize/bucketing must not break the steady-state
    fast path — one compile, zero retraces after the first step."""
    flags.set_flags({"donate_state": True, "metrics": True})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(
        mesh=_mesh(8), comm_quantize="int8", comm_buffer_mb=4.0)

    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        feed = _feed()
        miss0 = reg.get("executor.cache_miss").value()
        exe.run(compiled, feed=feed, fetch_list=[loss], return_numpy=False)
        traces1 = reg.get("executor.traces").value()
        for _ in range(5):
            exe.run(compiled, feed=feed, fetch_list=[loss],
                    return_numpy=False)
        assert reg.get("executor.cache_miss").value() - miss0 == 1
        assert reg.get("executor.traces").value() == traces1


def _cc_counters(reg):
    def val(name):
        m = reg.get(name)
        return m.value() if m is not None else 0
    return (val("executor.compile_cache_hit"),
            val("executor.compile_cache_miss"),
            val("executor.traces"))


@needs_devices
def test_compile_cache_warm_start_under_comm_quantize(_flags_guard, tmp_path):
    """Acceptance: the persistent AOT cache still round-trips when the plan
    carries comm options (they feed the key via the plan fingerprint), and
    a warm run deserializes without re-tracing."""
    flags.set_flags({"donate_state": True, "metrics": True,
                     "compile_cache_dir": str(tmp_path)})
    reg = monitor.default_registry()
    main, startup, loss = _build_net(seed=7)
    compiled = static.CompiledProgram(main).with_sharding(
        mesh=_mesh(8), comm_quantize="int8")

    cold, _ = _train(compiled, main, startup, loss)
    assert sorted(tmp_path.glob("*.pdtc")), "cold run stored no executables"
    h0, m0, t0 = _cc_counters(reg)
    warm, _ = _train(compiled, main, startup, loss)
    h1, m1, t1 = _cc_counters(reg)
    assert warm == cold                      # bitwise: same executable bytes
    assert h1 - h0 >= 1
    assert t1 - t0 == 0                      # deserialization never re-traces

    # a different comm config must MISS, not replay the quantized executable
    other = static.CompiledProgram(main).with_sharding(
        mesh=_mesh(8), comm_quantize="fp8")
    h0, m0, _ = _cc_counters(reg)
    _train(other, main, startup, loss, steps=1)
    _, m1, _ = _cc_counters(reg)
    assert m1 - m0 >= 1


# ---------------------------------------------------------------------------
# collbench selfcheck rides tier-1
# ---------------------------------------------------------------------------

@needs_devices
def test_collbench_selfcheck():
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, PYTHONPATH=str(repo) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("JAX_PLATFORMS", None)  # collbench forces its own host topology
    proc = subprocess.run(
        [sys.executable, "-m", "tools.collbench", "--selfcheck"],
        cwd=repo, capture_output=True, text=True, timeout=580, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["parity"]["unquantized_bitwise"] is True
    int8 = [c for c in rec["configs"]
            if c["compress"] == "int8" and c["schedule"] == "flat"]
    assert int8 and int8[0]["wire_ratio"] <= 0.30
