"""Pallas vision kernels + int8 inference path (ops/pallas/conv_fused.py,
pooling.py, int8.py + the quant_infer pass and dispatch wiring).

The PR-13 contract pinned here:
  * fused conv+BN+act and the training-mode BN-stats+act kernel match the
    unfused XLA reference (forward AND gradients) in interpret mode on CPU
    CI — the same code path a TPU runs compiled;
  * NHWC pooling kernels match lax.reduce_window on odd spatial shapes and
    with padding; the exclusive-avg-with-padding case is gated OUT of the
    kernel (`supported()` false) and the functional layer falls back;
  * the graph-level conv+BN+act fusion now fires in TRAINING graphs
    (backward_region references only Loss+Params, never intermediates)
    with golden parity through the optimizer step;
  * the `quant_infer` pass folds PTQ artifacts into `quant_conv2d` /
    `quant_mul`: flag-off lowering is BITWISE the pre-rewrite fake-quant
    graph, the Pallas int8 path stays within a bounded error of it, and a
    quantized residual block holds golden parity end to end;
  * per-channel weight scales live on the OUTPUT-channel axis — conv OIHW
    axis 0, mul/matmul LAST axis (axis 0 is the contraction dim; reducing
    over the wrong axis silently breaks per-channel dequant);
  * the kernel-config fingerprint rides both executor cache layers: zero
    steady-state retraces, a kernel-flag flip is exactly one clean
    recompile (and flipping back re-traces nothing);
  * xprof prices the custom-calls Pallas kernels lower to (>= 90% flops
    attribution coverage on a representative synthetic HLO);
  * a PTQ'd tenant registered with ``add_tenant(quantize=True)`` serves
    through the rewritten program with parity;
  * `python -m tools.kernelbench --selfcheck` and the metricsdump
    known-names lint pass in child processes.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F
import paddle_tpu.static as static
from paddle_tpu.core import flags
from paddle_tpu.ops.pallas import config as pcfg
from paddle_tpu.ops.pallas import conv_fused as cf
from paddle_tpu.ops.pallas import int8 as pint8
from paddle_tpu.ops.pallas import pooling as ppool
from paddle_tpu.slim import quant_static
from paddle_tpu.slim.quant import weight_quant_axis
from paddle_tpu.static import layers as L
from paddle_tpu.static import passes as P
from paddle_tpu.utils import monitor, xprof

REPO = Path(__file__).resolve().parents[1]
RNG = np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _fresh_programs():
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        yield main, startup


@pytest.fixture
def _flags_guard():
    saved = flags.get_flags(["metrics", "opt_passes", "compile_cache_dir",
                             "use_pallas_conv_fused", "use_pallas_pool",
                             "use_pallas_int8"])
    yield
    flags.set_flags(saved)


@pytest.fixture
def _tpu_gate(monkeypatch):
    """Force `kernel_enabled` open on CPU CI: kernels run in Pallas
    interpret mode, exercising the exact code a TPU compiles."""
    monkeypatch.setattr(pcfg, "backend_is_tpu", lambda: True)


def _init_state(startup):
    scope = static.Scope()
    with static.scope_guard(scope):
        static.Executor().run(startup)
        return {k: np.asarray(scope.find_var(k)) for k in scope.keys()}


def _op_types(program):
    return [op.type for op in program.global_block().ops]


def _scope_state(scope):
    return {k: np.asarray(scope.find_var(k)) for k in scope.keys()}


# ---------------------------------------------------------------------------
# kernel parity: fused conv+BN+act (inference epilogue)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stride,padding,act", [
    ((1, 1), (1, 1), "relu"),
    ((2, 2), (0, 0), ""),
    ((1, 1), (2, 2), "sigmoid"),
])
def test_conv2d_bn_act_kernel_parity(stride, padding, act):
    x = RNG.normal(size=(2, 8, 8, 8)).astype(np.float32)
    w = (RNG.normal(size=(16, 8, 3, 3)) * 0.2).astype(np.float32)
    a = RNG.uniform(0.5, 1.5, size=(16,)).astype(np.float32)
    b = RNG.normal(size=(16,)).astype(np.float32)

    got = cf.conv2d_bn_act(x, w, a, b, stride=stride, padding=padding,
                           act=act)
    ref = jax.lax.conv_general_dilated(
        x, jnp.transpose(w, (2, 3, 1, 0)), stride,
        [(padding[0], padding[0]), (padding[1], padding[1])],
        dimension_numbers=("NHWC", "HWIO", "NHWC")) * a + b
    if act == "relu":
        ref = jax.nn.relu(ref)
    elif act == "sigmoid":
        ref = jax.nn.sigmoid(ref)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_fused_bn_act_train_parity_and_grads():
    x = RNG.normal(size=(2, 4, 4, 8)).astype(np.float32)
    gamma = RNG.uniform(0.5, 1.5, size=(8,)).astype(np.float32)
    beta = RNG.normal(size=(8,)).astype(np.float32)
    eps = 1e-5

    def ref_fn(x, gamma, beta):
        x2 = x.reshape(-1, x.shape[-1])
        mean = x2.mean(0)
        var = x2.var(0)
        y = (x2 - mean) / jnp.sqrt(var + eps) * gamma + beta
        return jax.nn.relu(y).reshape(x.shape), mean, var

    y, mean, var = cf.fused_bn_act_train(x, gamma, beta, eps, act="relu")
    ry, rmean, rvar = ref_fn(x, gamma, beta)
    np.testing.assert_allclose(y, ry, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mean, rmean, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, rvar, rtol=1e-5, atol=1e-6)

    # the custom VJP must match AD through the unfused reference
    fused = lambda x, g, b: cf.fused_bn_act_train(x, g, b, eps, act="relu")
    loss = lambda fn: lambda *args: jnp.sum(fn(*args)[0] ** 2)
    g = jax.grad(loss(fused), argnums=(0, 1, 2))(x, gamma, beta)
    rg = jax.grad(loss(ref_fn), argnums=(0, 1, 2))(x, gamma, beta)
    for got, want in zip(g, rg):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# NHWC pooling: odd shapes, padding, and the gated-out fallback
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape,kernel,stride,padding", [
    ((2, 7, 9, 8), (3, 3), (2, 2), (1, 1)),   # odd spatial + padding
    ((1, 5, 5, 4), (2, 2), (1, 1), (0, 0)),   # unit stride
    ((2, 8, 6, 8), (3, 2), (2, 1), (0, 1)),   # asymmetric everything
])
def test_pooling_kernel_parity(shape, kernel, stride, padding):
    x = RNG.normal(size=shape).astype(np.float32)
    window = (1,) + kernel + (1,)
    strides = (1,) + stride + (1,)
    pads = [(0, 0), (padding[0], padding[0]), (padding[1], padding[1]),
            (0, 0)]

    got_max = ppool.max_pool2d_nhwc(x, kernel, stride, padding)
    ref_max = jax.lax.reduce_window(x, -np.inf, jax.lax.max, window,
                                    strides, pads)
    np.testing.assert_array_equal(got_max, ref_max)

    # inclusive avg: padding contributes zeros, denominator is kh*kw
    got_avg = ppool.avg_pool2d_nhwc(x, kernel, stride, padding)
    ref_avg = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                    pads) / float(np.prod(kernel))
    np.testing.assert_allclose(got_avg, ref_avg, rtol=1e-6, atol=1e-6)


def test_avg_pool_exclusive_with_padding_is_gated_out(_tpu_gate):
    x = jnp.zeros((1, 8, 8, 128), jnp.float32)
    assert ppool.supported(x, (2, 2), (2, 2), (0, 0), "avg", True)
    # exclusive + padding needs per-position counts: XLA fallback
    assert not ppool.supported(x, (3, 3), (2, 2), (1, 1), "avg", True)
    assert ppool.supported(x, (3, 3), (2, 2), (1, 1), "avg", False)

    xr = RNG.normal(size=(1, 8, 8, 128)).astype(np.float32)
    got = F.avg_pool2d(xr, 3, stride=2, padding=1, exclusive=True,
                       data_format="NHWC")
    flags.set_flags({"use_pallas_pool": False})
    try:
        want = F.avg_pool2d(xr, 3, stride=2, padding=1, exclusive=True,
                            data_format="NHWC")
    finally:
        flags.set_flags({"use_pallas_pool": True})
    np.testing.assert_array_equal(got, want)


def test_functional_pool_dispatch_parity(_flags_guard, _tpu_gate):
    """With the gate open the functional layer routes NHWC pools through
    Pallas; the result must match the flag-off reduce_window path."""
    x = RNG.normal(size=(2, 9, 9, 128)).astype(np.float32)
    reg = monitor.default_registry()
    flags.set_flags({"metrics": True})
    base = reg.get("pallas.kernel_calls")
    calls0 = sum(v for _l, v in base.samples()) if base is not None else 0

    got = F.max_pool2d(x, 2, stride=2, data_format="NHWC")
    flags.set_flags({"use_pallas_pool": False})
    want = F.max_pool2d(x, 2, stride=2, data_format="NHWC")
    np.testing.assert_array_equal(got, want)

    calls = reg.get("pallas.kernel_calls")
    calls1 = sum(v for _l, v in calls.samples()) if calls is not None else 0
    assert calls1 > calls0  # the Pallas branch actually ran


# ---------------------------------------------------------------------------
# graph fusion in TRAINING graphs
# ---------------------------------------------------------------------------

def test_fuse_conv_bn_act_train_mode_golden_parity(_fresh_programs):
    """backward_region references only Loss+Params, so the conv+BN+act
    triple fuses in training graphs too — parity through the SGD step,
    optimizer state included."""
    main, startup = _fresh_programs
    img = L.data("img", [4, 8, 8])
    c = L.conv2d(img, 4, 3, padding=1)
    out = L.batch_norm(c, act="relu")        # training-mode BN
    loss = L.mean(out)
    static.optimizer.SGD(learning_rate=0.1).minimize(loss)
    assert "backward_region" in _op_types(main)

    rewritten, report = P.PassManager(("fuse_conv_bn_act",)).apply(
        main, feed_names={"img"}, fetch_names=[loss.name])
    assert "fused_conv2d_bn_act" in _op_types(rewritten)
    assert "batch_norm" not in _op_types(rewritten)
    fused = next(op for op in rewritten.global_block().ops
                 if op.type == "fused_conv2d_bn_act")
    assert fused.attrs["is_test"] is False
    # running-stat writebacks survive (they alias the Mean/Variance inputs)
    assert fused.outputs["MeanOut"] == fused.inputs["Mean"]
    assert fused.outputs["VarianceOut"] == fused.inputs["Variance"]

    feed = {"img": RNG.normal(size=(4, 4, 8, 8)).astype(np.float32)}
    parity = P.golden_parity(main, rewritten, feed, [loss.name],
                             state=_init_state(startup), rtol=1e-4,
                             atol=1e-5)
    assert parity.ok, parity.to_text()


# ---------------------------------------------------------------------------
# int8 inference path: quant_infer pass + quant op lowerings
# ---------------------------------------------------------------------------

def _resnet_block(scope):
    """conv-BN-relu -> conv-BN -> +residual -> relu, PTQ'd in place."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup), static.scope_guard(scope):
        img = L.data("img", [8, 6, 6])
        c1 = L.conv2d(img, 8, 3, padding=1)
        b1 = L.batch_norm(c1, act="relu", is_test=True)
        c2 = L.conv2d(b1, 8, 3, padding=1)
        b2 = L.batch_norm(c2, is_test=True)
        out = L.relu(L.elementwise_add(b2, img))
        exe = static.Executor()
        exe.run(startup)
    return main, out, exe


def _ptq(main, out, exe, scope, feed):
    with static.scope_guard(scope):
        ptq = quant_static.PostTrainingQuantization(
            exe, program=main, feed_names=list(feed),
            batch_generator=lambda: iter([feed]), batch_nums=1, scope=scope)
        return ptq.quantize()


def test_quant_infer_resnet_block_golden_parity():
    scope = static.Scope()
    main, out, exe = _resnet_block(scope)
    feed = {"img": RNG.normal(size=(2, 8, 6, 6)).astype(np.float32)}
    qprog = _ptq(main, out, exe, scope, feed)
    assert "fake_quantize_dequantize_fixed_scale" in _op_types(qprog)

    rewritten, report = P.PassManager(P.QUANT_INFER_PIPELINE).apply(
        qprog, feed_names={"img"}, fetch_names=[out.name])
    types = _op_types(rewritten)
    assert types.count("quant_conv2d") == 2
    assert "conv2d" not in types
    # both convs' activation qdq ops folded into the quant op's in_scale
    assert "fake_quantize_dequantize_fixed_scale" not in types
    q = next(op for op in rewritten.global_block().ops
             if op.type == "quant_conv2d")
    assert q.attrs["in_scale"] > 0 and len(q.attrs["weight_scale"]) == 8

    parity = P.golden_parity(qprog, rewritten, feed, [out.name],
                             state=_scope_state(scope), rtol=1e-4,
                             atol=1e-5)
    assert parity.ok, parity.to_text()


def test_quant_conv_flag_off_is_bitwise_fallback():
    """Off-gate the quant ops must replay the exact fake-quant graph —
    the simulate path calls the same fixed-scale lowering, so parity is
    bitwise, not approximate."""
    scope = static.Scope()
    main, out, exe = _resnet_block(scope)
    feed = {"img": RNG.normal(size=(2, 8, 6, 6)).astype(np.float32)}
    qprog = _ptq(main, out, exe, scope, feed)
    rewritten, _report = P.PassManager(("quant_infer",)).apply(
        qprog, feed_names={"img"}, fetch_names=[out.name])
    assert "quant_conv2d" in _op_types(rewritten)

    parity = P.golden_parity(qprog, rewritten, feed, [out.name],
                             state=_scope_state(scope), rtol=0.0, atol=0.0)
    assert parity.ok, parity.to_text()


def _fc128(scope):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup), static.scope_guard(scope):
        x = L.data("x", [128])
        y = L.fc(x, 128, act="relu")
        exe = static.Executor()
        exe.run(startup)
    return main, y, exe


def test_quant_mul_pallas_int8_error_bound(_flags_guard, _tpu_gate):
    """The int8 Pallas matmul (interpret mode) must stay within a tight
    bound of the simulate path — the int8 grid recovery is exact, so the
    only drift is the fp32 dequant epilogue's summation order — and
    within the coarse PTQ error bound of the float program."""
    scope = static.Scope()
    main, y, exe = _fc128(scope)
    feed = {"x": RNG.normal(size=(8, 128)).astype(np.float32)}
    with static.scope_guard(scope):
        float_out, = exe.run(main, feed=feed, fetch_list=[y])

    qprog = _ptq(main, y, exe, scope, feed)
    rewritten, _report = P.PassManager(("quant_infer",)).apply(
        qprog, feed_names={"x"}, fetch_names=[y.name])
    assert "quant_mul" in _op_types(rewritten)

    with static.scope_guard(scope):
        sim_out, = exe.run(qprog, feed=feed, fetch_list=[y.name])
        flags.set_flags({"metrics": True})
        pal_out, = exe.run(rewritten, feed=feed, fetch_list=[y.name])
    np.testing.assert_allclose(pal_out, sim_out, rtol=1e-4, atol=1e-4)
    scale = np.abs(float_out).max()
    assert np.abs(pal_out - float_out).max() <= 0.05 * scale + 1e-3


def test_weight_quant_axis_contract():
    """Per-channel scales index the OUTPUT-channel axis: OIHW axis 0 for
    conv, the LAST axis for (in, out) mul weights.  Axis 0 of a mul
    weight is the contraction dim — a scale per *input* channel cannot be
    applied after the accumulation, so that reduction is the regression
    this test pins out."""
    assert weight_quant_axis("conv2d", 4) == 0
    assert weight_quant_axis("mul", 2) == 1
    assert weight_quant_axis("matmul", 2) == 1
    assert weight_quant_axis("unknown_op", 4) == 0

    scope = static.Scope()
    main, y, exe = _fc128(scope)
    with static.scope_guard(scope):
        wname = next(n for n in main.global_block().vars
                     if isinstance(main.global_block().vars[n],
                                   static.framework.Parameter)
                     and len(main.global_block().vars[n].shape) == 2)
        w_before = np.asarray(scope.find_var(wname)).copy()
    feed = {"x": RNG.normal(size=(8, 128)).astype(np.float32)}
    qprog = _ptq(main, y, exe, scope, feed)
    mul = next(op for op in qprog.global_block().ops if op.type == "mul")
    ws = np.asarray(mul.attrs["weight_scale"])
    assert ws.shape == (128,)
    np.testing.assert_allclose(
        ws, np.maximum(np.abs(w_before).max(axis=0), 1e-8), rtol=1e-6)


def test_qat_freeze_records_mul_quant_axis():
    """The QAT transform records quant_axis on the weight-qdq op so the
    freeze pass reduces over the right axes for mul weights too."""
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        x = L.data("x", [16])
        y = L.fc(x, 4)
        quant_static.QuantizationTransformPass().apply(main, startup)
        qdq = next(op for op in main.global_block().ops
                   if op.type ==
                   "fake_channel_wise_quantize_dequantize_abs_max")
        assert qdq.attrs["quant_axis"] == 1    # (in, out) weight: last axis
        scale_var = main.global_block().var(qdq.outputs["OutScale"][0])
        assert tuple(scale_var.shape) == (4,)  # one scale per OUTPUT unit


# ---------------------------------------------------------------------------
# executor cache identity: zero retraces, flag flip = one clean recompile
# ---------------------------------------------------------------------------

def test_kernel_fingerprint_zero_retraces_and_flag_flip(_flags_guard,
                                                        monkeypatch):
    flags.set_flags({"metrics": True})
    reg = monitor.default_registry()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [8])
        y = L.fc(x, 4, act="relu")
    feed = {"x": RNG.normal(size=(4, 8)).astype(np.float32)}
    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[y])
        t0 = reg.get("executor.traces").value()
        for _ in range(3):
            base_out, = exe.run(main, feed=feed, fetch_list=[y])
        assert reg.get("executor.traces").value() == t0  # steady state

        # flag flip (gate opens) -> different executable -> ONE recompile
        monkeypatch.setattr(pcfg, "backend_is_tpu", lambda: True)
        assert pcfg.cache_key_part() != ""
        gated_out, = exe.run(main, feed=feed, fetch_list=[y])
        t1 = reg.get("executor.traces").value()
        assert t1 == t0 + 1
        exe.run(main, feed=feed, fetch_list=[y])
        assert reg.get("executor.traces").value() == t1

        # flip back: the pre-flip executable is still cold-cached — no
        # retrace, and no stale cross-config hit either direction
        monkeypatch.setattr(pcfg, "backend_is_tpu", lambda: False)
        assert pcfg.cache_key_part() == ""
        back_out, = exe.run(main, feed=feed, fetch_list=[y])
        assert reg.get("executor.traces").value() == t1
        np.testing.assert_array_equal(base_out, back_out)
        np.testing.assert_allclose(gated_out, base_out, rtol=1e-5,
                                   atol=1e-6)


def test_kernel_fingerprint_rides_disk_cache_key(_tpu_gate):
    from paddle_tpu.static import compile_cache as cc

    main, _startup = static.Program(), static.Program()
    with static.program_guard(main, _startup):
        x = L.data("x", [8])
        y = L.fc(x, 4)
    feed = {"x": np.zeros((2, 8), np.float32)}
    common = dict(seed=0, fetch_names=[y.name], feed_arrays=feed,
                  donated={}, carried={}, donate=False,
                  plan_fingerprint=None)
    base = cc.build_cache_key(main, **common)
    assert cc.build_cache_key(main, **common, kernel="") == base
    fp = pcfg.cache_key_part()
    assert fp.startswith("pk") and "conv=1" in fp
    assert cc.build_cache_key(main, **common, kernel=fp) != base


# ---------------------------------------------------------------------------
# xprof: custom-call attribution coverage
# ---------------------------------------------------------------------------

_SYNTH_HLO = """\
ENTRY %main (p0: f32[2,10,10,64]) -> f32[2,8,8,64] {
  %p0 = f32[2,10,10,64]{3,2,1,0} parameter(0)
  %p1 = f32[3,3,64,64]{3,2,1,0} parameter(1)
  %p2 = f32[1,64]{1,0} parameter(2)
  %p3 = f32[1,64]{1,0} parameter(3)
  %q0 = s8[2,10,10,64]{3,2,1,0} parameter(4)
  %q1 = s8[3,3,64,64]{3,2,1,0} parameter(5)
  %m0 = s8[8,128]{1,0} parameter(6)
  %m1 = s8[128,128]{1,0} parameter(7)
  %cc0 = f32[2,8,8,64]{3,2,1,0} custom-call(f32[2,10,10,64]{3,2,1,0} %p0, f32[3,3,64,64]{3,2,1,0} %p1, f32[1,64]{1,0} %p2, f32[1,64]{1,0} %p3), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/fused_conv2d_bn_act.b0.i2/pallas.conv2d_bn_act"}
  %cc1 = f32[2,4,4,64]{3,2,1,0} custom-call(f32[2,8,8,64]{3,2,1,0} %cc0), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/pool2d.b0.i3/pallas.max_pool2d"}
  %cc2 = f32[2,8,8,64]{3,2,1,0} custom-call(s8[2,10,10,64]{3,2,1,0} %q0, s8[3,3,64,64]{3,2,1,0} %q1, f32[1,64]{1,0} %p2, f32[1,64]{1,0} %p3), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/quant_conv2d.b0.i4/pallas.int8_conv2d"}
  %cc3 = f32[8,128]{1,0} custom-call(s8[8,128]{1,0} %m0, s8[128,128]{1,0} %m1), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/quant_mul.b0.i5/pallas.int8_matmul"}
  %cc4 = f32[2,8,8,64]{3,2,1,0} custom-call(f32[2,8,8,64]{3,2,1,0} %cc0, f32[1,64]{1,0} %p2, f32[1,64]{1,0} %p3), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/fused_conv2d_bn_act.b0.i6/pallas.bn_act_train"}
  ROOT %out = f32[2,8,8,64]{3,2,1,0} add(f32[2,8,8,64]{3,2,1,0} %cc2, f32[2,8,8,64]{3,2,1,0} %cc4)
}
"""


def test_xprof_prices_pallas_custom_calls():
    """Every Pallas kernel family's custom-call is priced by its
    registered cost model (acceptance: >= 90% flops attribution coverage
    on a program dominated by Pallas custom-calls)."""
    report = xprof.build_report(_SYNTH_HLO, peaks=xprof.resolve_peaks(
        device_kind="test-device", peak_flops=200e9,
        peak_bytes_per_sec=40e9))
    regions = {r["region"]: r for r in report["regions"]}

    conv_flops = 2.0 * 2 * 8 * 8 * 64 * 64 * 3 * 3 + 3.0 * 2 * 8 * 8 * 64
    assert regions["fused_conv2d_bn_act.b0.i2"]["flops"] == conv_flops
    assert regions["quant_conv2d.b0.i4"]["flops"] == conv_flops
    assert regions["pool2d.b0.i3"]["flops"] > 0
    mm_flops = 2.0 * 8 * 128 * 128 + 3.0 * 8 * 128
    assert regions["quant_mul.b0.i5"]["flops"] == mm_flops
    assert regions["fused_conv2d_bn_act.b0.i6"]["flops"] == \
        3.0 * 2 * 8 * 8 * 64
    for key, r in regions.items():
        if key != "<unattributed>":
            assert r["attributed"], key
    assert report["totals"]["attribution_coverage"] >= 0.9


def test_unregistered_custom_call_prices_zero_not_crash():
    hlo = """\
ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  ROOT %cc = f32[8,8]{1,0} custom-call(f32[8,8]{1,0} %p0), custom_call_target="mystery", metadata={op_name="jit(f)/mystery_op"}
}
"""
    report = xprof.build_report(hlo)
    assert report["totals"]["flops_modeled"] == 0.0


# ---------------------------------------------------------------------------
# serving: quantized tenant registration
# ---------------------------------------------------------------------------

def test_serving_quantized_tenant_parity():
    from paddle_tpu.serving import Server

    scope = static.Scope()
    main, out, exe = _resnet_block(scope)
    feed = {"img": RNG.normal(size=(2, 8, 6, 6)).astype(np.float32)}
    qprog = _ptq(main, out, exe, scope, feed)
    with static.scope_guard(scope):
        ref, = exe.run(qprog, feed=feed, fetch_list=[out.name])

    srv = Server(bucket_edges=(1, 2, 4), max_wait_ms=2.0).start()
    try:
        srv.add_tenant("q", qprog, ["img"], [out], scope, quantize=True)
        tenant_types = _op_types(srv.tenants.get("q").program)
        assert "quant_conv2d" in tenant_types
        got = srv.submit("q", feed).result(timeout=120)[0]
    finally:
        srv.close()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# tools ride tier-1
# ---------------------------------------------------------------------------

def _child_env():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def test_kernelbench_selfcheck_subprocess():
    out = subprocess.run(
        [sys.executable, "-m", "tools.kernelbench", "--selfcheck"],
        cwd=REPO, env=_child_env(), capture_output=True, text=True,
        timeout=600)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "kernelbench selfcheck: OK" in out.stdout
    payload = json.loads(out.stdout.splitlines()[-1])
    assert {r["kernel"] for r in payload["kernels"]} >= {
        "conv2d_bn_act", "max_pool2d", "int8_conv2d"}


def test_metricsdump_lint_knows_pallas_names():
    out = subprocess.run(
        [sys.executable, "-m", "tools.metricsdump", "--lint"],
        cwd=REPO, env=_child_env(), capture_output=True, text=True,
        timeout=600)
    assert out.returncode == 0, out.stderr[-4000:]
