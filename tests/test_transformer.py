"""Transformer / ERNIE tests (analogue of reference test_transformer_api.py +
dygraph_to_static/test_bert.py numeric checks)."""
import numpy as np
import pytest

import paddle_tpu as pd
import paddle_tpu.nn as nn
from paddle_tpu import autograd


def _np(x):
    return np.asarray(x)


class TestMultiHeadAttention:
    def test_self_attention_shape(self):
        mha = nn.MultiHeadAttention(32, 4)
        x = pd.to_tensor(np.random.rand(2, 6, 32).astype(np.float32))
        out = mha(x)
        assert out.shape == (2, 6, 32)

    def test_cross_attention(self):
        mha = nn.MultiHeadAttention(32, 4)
        q = pd.to_tensor(np.random.rand(2, 3, 32).astype(np.float32))
        kv = pd.to_tensor(np.random.rand(2, 7, 32).astype(np.float32))
        assert mha(q, kv, kv).shape == (2, 3, 32)

    def test_additive_mask_blocks_positions(self):
        mha = nn.MultiHeadAttention(16, 2)
        mha.eval()
        x = pd.to_tensor(np.random.rand(1, 4, 16).astype(np.float32))
        # mask out position 3 for all queries
        mask = np.zeros((1, 1, 4, 4), np.float32)
        mask[..., 3] = -1e9
        out_masked = mha(x, attn_mask=pd.to_tensor(mask))
        # perturb key/value at position 3 — masked output must not change
        x2 = _np(x).copy()
        x2[0, 3] += 10.0
        out_masked2 = mha(pd.to_tensor(x2), attn_mask=pd.to_tensor(mask))
        np.testing.assert_allclose(_np(out_masked)[0, :3], _np(out_masked2)[0, :3],
                                   rtol=1e-4, atol=1e-5)

    def test_incremental_cache_matches_full(self):
        mha = nn.MultiHeadAttention(16, 2)
        mha.eval()
        x = pd.to_tensor(np.random.rand(1, 4, 16).astype(np.float32))
        causal = nn.Transformer.generate_square_subsequent_mask(4)[None, None]
        full = _np(mha(x, attn_mask=pd.to_tensor(np.asarray(causal))))
        cache = mha.gen_cache(x[:, :0])
        outs = []
        for t in range(4):
            step = x[:, t:t + 1]
            out, cache = mha(step, step, step, cache=cache)
            outs.append(_np(out))
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(full, inc, rtol=1e-4, atol=1e-5)


class TestEncoderDecoder:
    def test_encoder_stack(self):
        layer = nn.TransformerEncoderLayer(32, 4, 64, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 3)
        x = pd.to_tensor(np.random.rand(2, 5, 32).astype(np.float32))
        out = enc(x)
        assert out.shape == (2, 5, 32)
        # layers are distinct objects with distinct weights
        w0 = _np(enc.layers[0].linear1.weight.value)
        w1 = _np(enc.layers[1].linear1.weight.value)
        assert not np.allclose(w0, w1)

    def test_pre_vs_post_norm_differ(self):
        x = pd.to_tensor(np.random.rand(1, 4, 16).astype(np.float32))
        pd.seed(1)
        a = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0,
                                       normalize_before=True)
        pd.seed(1)
        b = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0,
                                       normalize_before=False)
        a.eval(); b.eval()
        assert not np.allclose(_np(a(x)), _np(b(x)))

    def test_full_transformer(self):
        model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=64,
                               dropout=0.0)
        src = pd.to_tensor(np.random.rand(2, 6, 32).astype(np.float32))
        tgt = pd.to_tensor(np.random.rand(2, 4, 32).astype(np.float32))
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(4)[None, None]
        out = model(src, tgt, tgt_mask=pd.to_tensor(np.asarray(tgt_mask)))
        assert out.shape == (2, 4, 32)


class TestErnie:
    @pytest.fixture(scope="class")
    def tiny_config(self):
        from paddle_tpu.text import ErnieConfig

        return ErnieConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                           num_attention_heads=2, intermediate_size=64,
                           max_position_embeddings=64)

    def test_forward_shapes(self, tiny_config):
        from paddle_tpu.text import ErnieModel

        model = ErnieModel(tiny_config)
        ids = pd.to_tensor(np.random.randint(1, 100, (2, 10)).astype(np.int32))
        seq, pooled = model(ids)
        assert seq.shape == (2, 10, 32)
        assert pooled.shape == (2, 32)

    def test_pad_mask_blocks_attention(self, tiny_config):
        from paddle_tpu.text import ErnieModel

        model = ErnieModel(tiny_config)
        model.eval()
        ids = np.random.randint(1, 100, (1, 8)).astype(np.int32)
        ids_padded = ids.copy()
        ids_padded[0, 6:] = 0  # pad_token_id
        seq1, _ = model(pd.to_tensor(ids_padded))
        # changing the padded tail tokens must not affect earlier positions
        ids_padded2 = ids_padded.copy()
        out1 = _np(seq1)[0, :6]
        seq2, _ = model(pd.to_tensor(ids_padded2))
        np.testing.assert_allclose(out1, _np(seq2)[0, :6], rtol=1e-5)

    def test_pretraining_loss_and_grads(self, tiny_config):
        from paddle_tpu.text import ErnieForPretraining, ErniePretrainingCriterion

        model = ErnieForPretraining(tiny_config)
        crit = ErniePretrainingCriterion(tiny_config.vocab_size)
        ids = pd.to_tensor(np.random.randint(1, 100, (2, 12)).astype(np.int32))
        mlm_labels = pd.to_tensor(np.random.randint(0, 100, (2, 3)).astype(np.int32))
        masked_pos = pd.to_tensor(np.array([[1, 4, 7], [2, 5, 8]], np.int32))
        nsp = pd.to_tensor(np.array([0, 1], np.int32))

        def loss_fn(ids_, mlm_, pos_, nsp_):
            scores, rel = model(ids_, masked_positions=pos_)
            return crit(scores, rel, mlm_, nsp_)

        params = autograd.parameters_dict(model)
        vag = autograd.value_and_grad(model, loss_fn)
        loss, grads = vag(params, ids, mlm_labels, masked_pos, nsp)
        assert np.isfinite(float(loss))
        # tied embedding gets gradient contributions from the LM head
        g_emb = grads["ernie.embeddings.word_embeddings.weight"]
        assert float(pd.sum(pd.abs(g_emb))) > 0

    def test_tiny_pretrain_step_reduces_loss(self, tiny_config):
        import jax
        from paddle_tpu.text import ErnieForPretraining, ErniePretrainingCriterion

        model = ErnieForPretraining(tiny_config)
        crit = ErniePretrainingCriterion(tiny_config.vocab_size)
        opt = pd.optimizer.Adam(learning_rate=1e-3)
        params = autograd.parameters_dict(model)
        state = opt.init(params)
        rng = np.random.RandomState(0)
        ids = rng.randint(1, 100, (4, 16)).astype(np.int32)
        pos = np.stack([rng.choice(16, 4, replace=False) for _ in range(4)]).astype(np.int32)
        mlm = rng.randint(0, 100, (4, 4)).astype(np.int32)
        nsp = rng.randint(0, 2, (4,)).astype(np.int32)

        def loss2(p, key):
            out = autograd.functional_call(
                model, p, (pd.to_tensor(ids),),
                {"masked_positions": pd.to_tensor(pos)}, rng=key)
            scores, rel = out
            return crit(scores, rel, pd.to_tensor(mlm), pd.to_tensor(nsp))

        @jax.jit
        def step(p, s, key):
            loss, grads = jax.value_and_grad(loss2)(p, key)
            p, s = opt.update(grads, s, p)
            return p, s, loss

        import jax.random as jr

        losses = []
        for i in range(8):
            params, state, loss = step(params, state, jr.key(i))
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
