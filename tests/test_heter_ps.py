"""Dedicated heterogeneous-PS test (closes the r04 VERDICT 'partial' on
N35/Heter-PS).

Reference contract (fleet heter_ps / operators/pscore HeterServer): the
SPARSE half of the model (embedding tables) lives on parameter-server
CPU memory while the DENSE half trains on the accelerator; trainers pull
rows for each batch, run the dense forward/backward on-device, push the
sparse gradients back, and dense params never leave the device.

TPU re-scope under test: host-RAM SparseTable served over TCP
(PSServer/RemoteSparseTable), dense path jitted; the embedding gradient
comes out of the SAME jax.grad as the dense gradients and is pushed
asynchronously (AsyncCommunicator), exactly the heterogeneous split."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed.ps import AsyncCommunicator, SparseTable
from paddle_tpu.distributed.ps_server import PSServer, RemoteSparseTable

DIM, VOCAB, BATCH = 8, 64, 16


@pytest.fixture
def server():
    srv = PSServer(SparseTable(dim=DIM, num_shards=2, optimizer="sgd",
                               seed=11))
    srv.start()
    yield srv
    srv.stop()


def test_heterogeneous_split_trains(server):
    remote = RemoteSparseTable([server.endpoint], dim=DIM)
    rng = np.random.default_rng(0)

    # dense half lives on-device; sparse half on the (remote) host table
    w_dense = jnp.asarray(rng.normal(0, 0.3, (DIM, 1)), jnp.float32)

    @jax.jit
    def dense_step(w, rows, y):
        def loss_fn(w_, rows_):
            pred = rows_ @ w_
            return jnp.mean((pred - y) ** 2)

        loss, (gw, grows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(w, rows)
        return loss, w - 0.1 * gw, grows

    # fixed synthetic task: ids -> target from a ground-truth embedding
    true_emb = rng.normal(0, 1, (VOCAB, DIM)).astype(np.float32)
    true_w = rng.normal(0, 1, (DIM, 1)).astype(np.float32)

    comm = AsyncCommunicator(remote, lr=0.3)
    comm.start()
    losses = []
    try:
        for step in range(60):
            ids = rng.integers(0, VOCAB, (BATCH,))
            y = jnp.asarray(true_emb[ids] @ true_w, jnp.float32)
            rows = jnp.asarray(remote.pull(ids), jnp.float32)  # sparse pull
            loss, w_dense, grows = dense_step(w_dense, rows, y)
            comm.send(ids, np.asarray(grows))  # async sparse push
            losses.append(float(loss))
        comm.flush()
    finally:
        comm.stop()

    # the heterogeneous loop actually learned: loss dropped substantially
    assert np.mean(losses[-10:]) < 0.5 * np.mean(losses[:10]), losses
    # sparse rows really live server-side (updated remotely, not locally)
    assert remote.num_rows > 0
    st = remote.state_dict()
    assert st["rows"].shape[1] == DIM
    remote.close()


def test_dense_params_never_cross_the_wire(server):
    """The dense half must stay device-side: only id/row/grad arrays go
    through the transport (spied), never the dense weight matrix."""
    remote = RemoteSparseTable([server.endpoint], dim=DIM)
    sent_shapes = []
    conn = remote._conns[0]
    orig_call = conn.call

    def spy(op, arrays, **kw):
        sent_shapes.extend(tuple(np.asarray(a).shape) for a in arrays)
        return orig_call(op, arrays, **kw)

    conn.call = spy
    rng = np.random.default_rng(1)
    w_dense = jnp.asarray(rng.normal(0, 0.3, (DIM, 1)), jnp.float32)
    ids = rng.integers(0, VOCAB, (BATCH,))
    rows = jnp.asarray(remote.pull(ids), jnp.float32)
    grows = jax.grad(lambda r: jnp.sum((r @ w_dense) ** 2))(rows)
    remote.push(ids, np.asarray(grows), lr=0.1)
    # everything on the wire is batch-shaped sparse traffic
    assert (DIM, 1) not in sent_shapes  # the dense weight never crossed
    assert any(s == (BATCH, DIM) for s in sent_shapes)  # rows/grads did
    remote.close()
