"""jit.to_static / TracedLayer / jit.save+load and the inference Predictor.

Mirrors the reference's dygraph_to_static numeric-equality tests
(unittests/dygraph_to_static/: dygraph output == converted static output)
and the inference API tests (inference/tests/api/) at the Python surface.
"""
import os

import numpy as np
import pytest

import paddle_tpu as pd
import paddle_tpu.nn as nn
from paddle_tpu import inference, jit


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _x(batch=3, seed=0):
    return np.random.RandomState(seed).rand(batch, 8).astype(np.float32)


def test_to_static_matches_dygraph():
    net = SmallNet()
    x = _x()
    eager = np.asarray(net(pd.to_tensor(x)))
    static_fn = jit.to_static(net.forward)
    out = np.asarray(static_fn(x))
    np.testing.assert_allclose(eager, out, rtol=1e-5)
    # cache hit on same signature, recompile on new shape
    out2 = np.asarray(static_fn(_x(batch=5)))
    assert out2.shape == (5, 4)


def test_to_static_on_layer_object():
    net = jit.to_static(SmallNet())
    out = net(_x())
    assert np.asarray(out).shape == (3, 4)


def test_to_static_plain_function():
    @jit.to_static
    def f(a, b):
        return pd.matmul(a, b) + 1.0

    a = np.eye(4, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(f(a, a)), a @ a + 1.0)


def test_traced_layer_and_roundtrip(tmp_path):
    net = SmallNet()
    x = _x()
    out, traced = jit.TracedLayer.trace(net, [x])
    np.testing.assert_allclose(np.asarray(traced(x)), np.asarray(out), rtol=1e-6)
    prefix = str(tmp_path / "traced_model")
    traced.save_inference_model(prefix)
    assert os.path.exists(prefix + ".pdmodel")


def test_jit_save_load_numeric_equality(tmp_path):
    net = SmallNet()
    net.eval()
    x = _x(batch=2, seed=1)
    ref = np.asarray(net(pd.to_tensor(x)))

    prefix = str(tmp_path / "model")
    jit.save(net, prefix, input_spec=[jit.InputSpec([2, 8], "float32", "x")])

    loaded = jit.load(prefix)
    np.testing.assert_allclose(np.asarray(loaded(x)), ref, rtol=1e-5)
    # state dict preserved for fine-tune reload
    sd = loaded.state_dict()
    assert any(k.endswith("weight") or "fc1" in k for k in sd)
    net2 = SmallNet()
    net2.set_state_dict({k: v for k, v in sd.items()})
    np.testing.assert_allclose(np.asarray(net2(pd.to_tensor(x))), ref, rtol=1e-5)
    with pytest.raises(RuntimeError):
        loaded.train()


def test_predictor_handles_and_run(tmp_path):
    net = SmallNet()
    net.eval()
    x = _x(batch=4, seed=2)
    ref = np.asarray(net(pd.to_tensor(x)))

    prefix = str(tmp_path / "serving")
    jit.save(net, prefix, input_spec=[jit.InputSpec([4, 8], "float32", "input")])

    cfg = inference.Config(prefix)
    cfg.enable_memory_optim()
    predictor = inference.create_predictor(cfg)

    assert predictor.get_input_names() == ["input"]
    h = predictor.get_input_handle("input")
    h.copy_from_cpu(x)
    outs = predictor.run()
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)
    oh = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(oh.copy_to_cpu(), ref, rtol=1e-5)

    # positional 2.0-style run
    outs2 = predictor.run([x])
    np.testing.assert_allclose(outs2[0], ref, rtol=1e-5)

    # static-shape contract is enforced loudly
    with pytest.raises(ValueError, match="static shapes"):
        h.copy_from_cpu(_x(batch=7))


def test_predictor_requires_inputs(tmp_path):
    net = SmallNet()
    prefix = str(tmp_path / "m")
    jit.save(net, prefix, input_spec=[jit.InputSpec([1, 8], "float32")])
    p = inference.create_predictor(inference.Config(prefix))
    with pytest.raises(RuntimeError, match="not set"):
        p.run()
