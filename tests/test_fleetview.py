"""Fleet view (tools/fleetview.py): job-level telemetry aggregation.

The acceptance bar is the 3-rank ``launch --telemetry_port`` integration
test: an injected 5x straggler rank must be attributed identically by
fleetview's histogram-derived skew view and the watchdog's heartbeat-lag
view (``report["watchdog"]["agrees"]``), and the merged report's flat
``record`` block must feed ``tools/benchdiff`` unmodified.  The merge
unit tests pin degraded-fleet behavior (unreachable ranks, disagreeing
watchdog) on synthetic scrapes; ``--selfcheck`` rides tier-1 both
in-process and as the CLI subprocess.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from tools import benchdiff, fleetview

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# synthetic-scrape helpers (merge() consumes scrape_rank()'s shape)
# ---------------------------------------------------------------------------
def _scrape(rank, step_ms, count=20, goodput=95.0, comm_dp=None,
            watchdog=None, ledger_records=()):
    parsed = {
        ("executor_step_time_ms_sum", ()): step_ms * count,
        ("executor_step_time_ms_count", ()): float(count),
        ("train_goodput_pct", ()): goodput,
    }
    if comm_dp is not None:
        parsed[("comm_allreduce_bytes_sum",
                (("axis", "dp"), ("dtype", "fp32")))] = comm_dp
    healthz = {"status": "ok", "rank": rank, "_status": 200}
    if watchdog is not None:
        healthz["watchdog"] = watchdog
    return {
        "endpoint": f"127.0.0.1:{9100 + rank}",
        "metrics": parsed,
        "healthz": healthz,
        "ledger": {"_status": 200, "last_seq": len(ledger_records),
                   "truncated": False,
                   "bands": {"comm": 2.0, "mem": 1.5, "roofline": None},
                   "records": list(ledger_records)},
    }


def test_merge_skew_straggler_and_record_block():
    report = fleetview.merge([_scrape(0, 10.0), _scrape(1, 50.0),
                              _scrape(2, 10.0)])
    assert report["nranks"] == 3 and report["healthy_ranks"] == 3
    assert report["skew"]["stragglers"] == [1]
    assert report["skew"]["max_over_median"] == pytest.approx(5.0)
    assert report["ranks"]["1"]["step_time_ms"]["mean"] == 50.0
    rec = report["record"]["fleet"]
    assert rec["stragglers"] == 1 and rec["step_time_skew"] == 5.0
    assert rec["goodput_min_pct"] == 95.0
    json.dumps(report)


def test_merge_tolerates_unreachable_rank():
    dead = {"endpoint": "127.0.0.1:9103",
            "metrics": {"error": "ConnectionRefusedError(111)"},
            "healthz": {"error": "ConnectionRefusedError(111)"},
            "ledger": {"error": "ConnectionRefusedError(111)"}}
    report = fleetview.merge([_scrape(0, 10.0), dead])
    assert report["nranks"] == 2 and report["healthy_ranks"] == 1
    row = report["ranks"]["1"]
    assert row["status"] == "unreachable" and "error" in row
    assert "step_time_ms" not in row
    # one live rank: no leave-one-out baseline, no false straggler
    assert report["skew"]["stragglers"] == []
    json.dumps(report)


def test_merge_watchdog_cross_check_agrees_and_disagrees():
    wd = {"stragglers": {"front_step": 120, "stragglers": [1],
                         "ranks": {}}}
    report = fleetview.merge([_scrape(0, 10.0, watchdog=wd),
                              _scrape(1, 50.0), _scrape(2, 10.0)])
    assert report["watchdog"]["source_rank"] == 0
    assert report["watchdog"]["stragglers"] == [1]
    assert report["watchdog"]["agrees"] is True
    # a heartbeat view naming a different rank must be flagged, not hidden
    wd_bad = {"stragglers": {"front_step": 120, "stragglers": [2],
                             "ranks": {}}}
    report = fleetview.merge([_scrape(0, 10.0, watchdog=wd_bad),
                              _scrape(1, 50.0), _scrape(2, 10.0)])
    assert report["watchdog"]["agrees"] is False
    # no rank serving a watchdog section -> explicit None, not a crash
    report = fleetview.merge([_scrape(0, 10.0), _scrape(1, 50.0)])
    assert report["watchdog"] is None


def test_merge_comm_imbalance_and_calibration_table():
    led = [{"seq": 1, "kind": "compile",
            "key": {"program": "pfc", "plan": None, "mesh": None},
            "predicted": {"peak_hbm_bytes": 120.0},
            "measured": {"mem_total_bytes": 100.0},
            "drift": {"comm": None, "mem": 1.2, "roofline": None},
            "band_violations": []},
           {"seq": 2, "kind": "window",
            "key": {"program": "pfc", "plan": None, "mesh": None},
            "predicted": {}, "measured": {"step_time_ms": 3.0},
            "drift": {"mem": 1.4}, "band_violations": []}]
    report = fleetview.merge([
        _scrape(0, 10.0, comm_dp=4096.0, ledger_records=led),
        _scrape(1, 12.0, comm_dp=1024.0)])
    assert report["comm_imbalance"]["dp"]["max_over_min"] == 4.0
    assert report["record"]["comm"]["imbalance_dp"] == 4.0
    cal = report["calibration"]
    assert cal["bands"]["mem"] == 1.5
    row = cal["programs"]["pfc|-|-"]
    assert row["records"] == 2
    assert row["drift"]["mem"] == 1.4          # latest
    assert row["worst_drift"]["mem"] == 1.4    # worst across records
    assert cal["worst_drift"]["mem"] == 1.4
    assert report["record"]["calibration"]["mem_drift"] == 1.4
    assert report["ranks"]["0"]["ledger_records"] == 2
    # text renderer covers the populated report end-to-end
    text = fleetview.render_text(report)
    assert "calibration" in text and "comm[dp]" in text


# ---------------------------------------------------------------------------
# selfcheck: tier-1 CI, in-process and as the CLI
# ---------------------------------------------------------------------------
def test_selfcheck_in_process():
    assert fleetview.selfcheck(verbose=False) == 0


def test_fleetview_cli_selfcheck():
    r = subprocess.run(
        [sys.executable, "-m", "tools.fleetview", "--selfcheck"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["selfcheck"] == "pass" and doc["stragglers"] == [1]


def test_cli_requires_endpoints():
    with pytest.raises(SystemExit):
        fleetview.main(["--format", "json"])


# ---------------------------------------------------------------------------
# the acceptance integration: 3 ranks, one injected 5x straggler, both
# attribution views agree, benchdiff consumes the merged report
# ---------------------------------------------------------------------------
def _free_port_base():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_launch_three_ranks_straggler_attributed_by_both_views(tmp_path):
    from paddle_tpu.distributed.launch import launch

    out = tmp_path / "out"
    hb = tmp_path / "hb"
    out.mkdir()
    hb.mkdir()
    base = _free_port_base()
    report_path = out / "report.json"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import json, os, time
        import paddle_tpu  # bootstrap starts this rank's telemetry plane
        from paddle_tpu.elastic.membership import ElasticMember
        from paddle_tpu.utils import ledger, monitor, telemetry, watchdog

        OUT = {str(out)!r}
        HB = {str(hb)!r}
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        srv = telemetry.get_server()
        assert srv is not None and srv.port == {base} + rank, srv

        member = ElasticMember(HB, rank=rank, world_size=3,
                               interval_s=0.05, dead_after_s=60.0).start()
        wd = watchdog.Watchdog(heartbeat_dir=HB)
        telemetry.register_health_provider("watchdog", wd.report)
        # one calibration record per rank so the merged /ledger table has
        # real legs to aggregate
        ledger.ledger().append(
            "compile", {{"program": "itest", "plan": None, "mesh": None}},
            {{"peak_hbm_bytes": 120.0}}, {{"mem_total_bytes": 100.0}})

        def wait_all(stem, deadline_s=30):
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                if all(os.path.exists(os.path.join(OUT, stem % r))
                       for r in range(3)):
                    return True
                time.sleep(0.05)
            return False

        # start barrier: heartbeat step lag must measure per-step speed,
        # not the ranks' import-time skew
        open(os.path.join(OUT, "boot.%d" % rank), "w").close()
        assert wait_all("boot.%d"), "boot barrier timed out"

        STEP_MS = 50.0 if rank == 1 else 10.0   # rank 1 is the 5x straggler
        hist = monitor.histogram("executor.step_time_ms", "")
        step = 0
        deadline = time.time() + 1.2
        while time.time() < deadline:
            time.sleep(STEP_MS / 1000.0)
            step += 1
            hist.observe(STEP_MS)
            wd.observe_step(step, STEP_MS)
            member.set_step(step)

        open(os.path.join(OUT, "ready.%d" % rank), "w").close()
        assert wait_all("ready.%d"), "ready barrier timed out"

        if rank == 0:
            time.sleep(0.3)   # let every rank's final heartbeat land
            from tools import fleetview
            scrapes = [fleetview.scrape_rank("127.0.0.1:%d" % ({base} + r))
                       for r in range(3)]
            report = fleetview.merge(scrapes)
            tmp = os.path.join(OUT, ".report.tmp")
            with open(tmp, "w") as f:
                json.dump(report, f)
            os.replace(tmp, {str(report_path)!r})
        else:
            deadline = time.time() + 30
            while (time.time() < deadline
                   and not os.path.exists({str(report_path)!r})):
                time.sleep(0.1)
        member.stop()
    """))
    rc = launch(str(script), [], nproc=3, telemetry_port=base,
                backend_env=f"JAX_PLATFORMS=cpu,PYTHONPATH={REPO},"
                            "PDTPU_FLAGS_metrics=1")
    assert rc == 0
    report = json.load(open(report_path))

    # both attribution views name exactly the injected straggler
    assert report["nranks"] == 3 and report["healthy_ranks"] == 3
    assert report["skew"]["stragglers"] == [1]
    assert report["watchdog"]["stragglers"] == [1]
    assert report["watchdog"]["agrees"] is True
    assert report["skew"]["max_over_median"] > 2.0   # 50ms vs 10ms means
    # per-rank planes survived the wire: step means ordered as injected
    means = {r: report["ranks"][r]["step_time_ms"]["mean"]
             for r in ("0", "1", "2")}
    assert means["1"] > 2 * max(means["0"], means["2"])
    # goodput rollup came from the live watchdog gauges
    assert report["goodput"]["min_pct"] is not None
    # the merged calibration table joined every rank's /ledger leg
    cal = report["calibration"]
    assert cal["programs"]["itest|-|-"]["records"] == 3
    assert cal["worst_drift"]["mem"] == pytest.approx(1.2)

    # the report is a benchdiff-consumable artifact as written to disk
    metrics = benchdiff.extract_metrics(str(report_path))
    assert metrics["fleet.stragglers"][0] == 1.0
    assert metrics["fleet.step_time_skew"][0] > 2.0
    assert metrics["calibration.mem_drift"][0] == pytest.approx(1.2)
    same = benchdiff.diff_metrics(metrics, metrics)
    assert same["verdict"] == "pass"


# ---------------------------------------------------------------------------
# the job-level alert plane: dedupe, state precedence, sparklines, gate
# ---------------------------------------------------------------------------
def _alerts_leg(state, slo_name="s", severity="page", burn_short=5.0,
                burn_long=3.0):
    return {"_status": 200, "alerts": [
        {"slo": slo_name, "severity": severity, "state": state,
         "metric": "t.m", "burn_short": burn_short,
         "burn_long": burn_long}]}


def test_alerts_section_dedupes_and_state_precedence():
    s0 = {"endpoint": "e0", "alerts": _alerts_leg("resolved",
                                                  burn_short=1.0)}
    s1 = {"endpoint": "e1", "alerts": _alerts_leg("firing", burn_short=9.0)}
    sec = fleetview._alerts_section([s0, s1], [0, 1])
    assert sec["ranks_reporting"] == 2
    (row,) = sec["alerts"]                   # ONE job alert, not two
    assert row["state"] == "firing"          # firing on ANY rank wins
    assert row["ranks"] == [0, 1]
    assert row["burn_short"] == 9.0          # worst burn survives the merge
    assert sec["firing"] == [row]
    # ok states are dropped; pending beats resolved; different (slo,
    # severity) pairs stay separate rows
    s2 = {"endpoint": "e0", "alerts": {"_status": 200, "alerts": [
        {"slo": "s", "severity": "page", "state": "ok"},
        {"slo": "q", "severity": "ticket", "state": "pending",
         "burn_short": 2.0, "burn_long": 2.0}]}}
    s3 = {"endpoint": "e1", "alerts": _alerts_leg(
        "resolved", slo_name="q", severity="ticket", burn_short=0.1,
        burn_long=0.1)}
    sec = fleetview._alerts_section([s2, s3], [0, 1])
    (row,) = sec["alerts"]
    assert (row["slo"], row["state"]) == ("q", "pending")
    assert sec["firing"] == []
    # an unreachable /alerts leg is skipped, never a crash
    dead = {"endpoint": "e", "alerts": {"error": "ConnectionRefused"}}
    sec = fleetview._alerts_section([dead], [0])
    assert sec == {"ranks_reporting": 0, "alerts": [], "firing": []}


def test_burn_history_and_sparkline():
    scr = {"endpoint": "e", "history": {"_status": 200, "series": {
        "slo.burn_rate{slo=s,window=5s}": {
            "samples": [[1, 0.0, 0.5], [2, 1.0, 2.0]]},
        "t.other": {"samples": [[3, 0.0, 1.0]]}}}}
    bh = fleetview._burn_history([scr], [0])
    assert list(bh) == ["slo.burn_rate{slo=s,window=5s}"]
    assert bh["slo.burn_rate{slo=s,window=5s}"]["0"] == [0.5, 2.0]
    # sparklines: empty-safe, normalized to the series max, width-thinned
    assert fleetview._sparkline([]) == ""
    line = fleetview._sparkline([0.0, 0.0, 8.0])
    assert len(line) == 3
    assert line[0] == fleetview._SPARK_GLYPHS[0]
    assert line[-1] == fleetview._SPARK_GLYPHS[-1]
    assert len(fleetview._sparkline([float(i) for i in range(100)],
                                    width=24)) == 24


def test_merge_alerts_ride_report_record_and_text():
    s0 = _scrape(0, 10.0)
    s1 = _scrape(1, 10.0)
    s0["alerts"] = _alerts_leg("firing")
    s1["alerts"] = _alerts_leg("firing")
    s0["history"] = {"_status": 200, "series": {
        "slo.burn_rate{slo=s,window=5s}": {
            "samples": [[1, 0.0, 0.0], [2, 1.0, 6.0]]}}}
    report = fleetview.merge([s0, s1])
    assert report["alerts"]["ranks_reporting"] == 2
    assert report["alerts"]["alerts"][0]["ranks"] == [0, 1]
    assert report["record"]["slo"] == {"alerts_firing": 1,
                                       "pages_firing": 1}
    text = fleetview.render_text(report)
    assert "FIRING" in text and "s:page" in text
    assert "slo.burn_rate{slo=s,window=5s}" in text
    # ranks without /alerts legs (older planes) degrade to an empty section
    empty = fleetview.merge([_scrape(0, 10.0)])
    assert empty["alerts"] == {"ranks_reporting": 0, "alerts": [],
                               "firing": []}
    assert empty["record"]["slo"]["alerts_firing"] == 0
    json.dumps(report)
