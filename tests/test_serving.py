"""Serving subsystem (paddle_tpu/serving/): bucketed coalescing frontend,
continuous-batching decode, tenant LRU + quotas, SLO load shed, and the
capi worker's pipelined request-id framing.

The two load-bearing contracts pinned bitwise here:

* PADDING PARITY — the real rows of a padded bucket batch are bitwise
  identical to running each request alone.  Holds for row-independent
  graphs whose matmul shapes are not degenerate (contraction dim >= 8 and
  output dim >= 2 on XLA:CPU; tinier gemms can take batch-size-dependent
  kernel strategies — a kernel-choice property, not a padding artifact).
* DECODE PARITY — a sequence's generated tokens are identical no matter
  which slot it decodes in, who its neighbors are, or when it joins.

Plus zero steady-state retraces per bucket (``executor.traces``).
"""
import os
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu import serving
from paddle_tpu.core import flags
from paddle_tpu.core.errors import NotFoundError
from paddle_tpu.serving import (AdmissionError, ContinuousBatcher,
                                QuotaExceededError, SLOPolicy, Server,
                                make_toy_lm)
from paddle_tpu.static import layers as L
from paddle_tpu.utils import monitor, trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _metrics_on():
    saved = flags.get_flags(["metrics"])
    flags.set_flags({"metrics": True})
    yield
    flags.set_flags(saved)


def _mlp_tenant(seed=3, in_dim=8, out_dim=4):
    """fc(8 -> 16 tanh -> 4): row-independent, batch-invariant dims."""
    main, startup = static.Program(), static.Program()
    main.random_seed = seed
    startup.random_seed = seed
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        x = L.data("x", [in_dim])
        y = L.fc(L.fc(x, 16, act="tanh"), out_dim)
        exe = static.Executor()
        exe.run(startup, scope=scope)
    return main, y, scope


def _int_tenant():
    """int32 in, int32 out (x*x + x): parity must hold exactly."""
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        x = L.data("x", [6], dtype="int32")
        y = L.elementwise_add(L.elementwise_mul(x, x), x)
        exe = static.Executor()
        exe.run(startup, scope=scope)
    return main, y, scope


def _bitwise_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return a.dtype == b.dtype and a.shape == b.shape and np.array_equal(
        a.view(np.uint8), b.view(np.uint8))


# ---------------------------------------------------------------------------
# frontend: coalescing, padding parity, concurrency, zero retraces
# ---------------------------------------------------------------------------
_PARITY_F32_SCRIPT = """
import numpy as np
import paddle_tpu.static as static
from paddle_tpu.serving import Server
from paddle_tpu.static import layers as L

main, startup = static.Program(), static.Program()
main.random_seed = startup.random_seed = 3
scope = static.Scope()
with static.program_guard(main, startup), static.scope_guard(scope):
    x = L.data("x", [8])
    y = L.fc(L.fc(x, 16, act="tanh"), 4)
    exe = static.Executor()
    exe.run(startup, scope=scope)
ref_exe = static.Executor()
rng = np.random.default_rng(0)
xs = [rng.normal(size=(1, 8)).astype(np.float32) for _ in range(24)]
srv = Server(bucket_edges=(1, 2, 4, 8), max_wait_ms=5.0).start()
srv.add_tenant("m", main, ["x"], [y], scope)
futs = [srv.submit("m", {"x": xv}) for xv in xs]
outs = [f.result(timeout=60)[0] for f in futs]
srv.close()
for xv, out in zip(xs, outs):
    ref = ref_exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)[0]
    assert out.dtype == ref.dtype and np.array_equal(out, ref), (out, ref)
print("PARITY_F32_OK")
"""


def test_bucket_padding_bitwise_parity_f32_subprocess():
    """Bitwise f32 parity holds in the PRODUCTION XLA configuration; the
    tier-1 conftest's compile-speed `xla_backend_optimization_level=0`
    disables the fusion that makes XLA:CPU gemms batch-invariant, so this
    test pins the contract in a child process with that flag stripped
    (the in-process int32 test below pins padding exactness regardless)."""
    env = _child_env()
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_backend_optimization_level" not in f)
    out = subprocess.run([sys.executable, "-c", _PARITY_F32_SCRIPT],
                         cwd=ROOT, env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "PARITY_F32_OK" in out.stdout


def test_bucket_padding_bitwise_parity_int32():
    main, y, scope = _int_tenant()
    with Server(bucket_edges=(1, 4, 8), max_wait_ms=5.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        xs = [np.arange(6, dtype=np.int32).reshape(1, 6) + i
              for i in range(10)]
        outs = [f.result(timeout=60)[0]
                for f in [srv.submit("m", {"x": x}) for x in xs]]
    for x, out in zip(xs, outs):
        assert _bitwise_equal(out, x * x + x)


def test_multi_row_requests_coalesce_and_slice_correctly():
    main, y, scope = _mlp_tenant()
    ref_exe = static.Executor()
    rng = np.random.default_rng(1)
    sizes = [3, 1, 2, 5, 1, 4]
    xs = [rng.normal(size=(n, 8)).astype(np.float32) for n in sizes]
    with Server(bucket_edges=(1, 2, 4, 8, 16), max_wait_ms=5.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        outs = [f.result(timeout=60)[0]
                for f in [srv.submit("m", {"x": x}) for x in xs]]
    for x, out in zip(xs, outs):
        assert out.shape == (x.shape[0], 4)
        ref = ref_exe.run(main, feed={"x": x}, fetch_list=[y],
                          scope=scope)[0]
        # tier-1 runs with xla_backend_optimization_level=0 (conftest),
        # where unfused CPU gemms are not batch-invariant; bitwise f32
        # parity is pinned by the subprocess test above
        np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)


def test_concurrent_submit_8_threads():
    # int32 elementwise model: results are exact, so 8 racing submitter
    # threads x arbitrary coalescing must still produce bitwise answers
    main, y, scope = _int_tenant()
    rng = np.random.default_rng(2)
    per_thread = 10
    xs = {(t, i): rng.integers(-50, 50, size=(1 + (t + i) % 3, 6)
                               ).astype(np.int32)
          for t in range(8) for i in range(per_thread)}
    results, errs = {}, []
    with Server(bucket_edges=(1, 2, 4, 8), max_wait_ms=1.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)

        def client(t):
            try:
                for i in range(per_thread):
                    out = srv.submit(
                        "m", {"x": xs[(t, i)]}).result(timeout=60)[0]
                    results[(t, i)] = out
            except Exception as e:  # noqa: BLE001 — surface in main thread
                errs.append(e)

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    assert not errs, errs
    assert len(results) == 8 * per_thread
    for key, x in xs.items():
        assert _bitwise_equal(results[key], x * x + x)


def test_zero_steady_state_retraces_per_bucket():
    main, y, scope = _mlp_tenant()
    reg = monitor.default_registry()
    rng = np.random.default_rng(3)
    with Server(bucket_edges=(1, 2, 4), max_wait_ms=0.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        # warm every bucket once (each compiles its own entry)
        for n in (1, 2, 4):
            srv.submit("m", {"x": rng.normal(size=(n, 8)).astype(
                np.float32)}).result(timeout=60)
        traces0 = reg.get("executor.traces").value()
        hot0 = len(srv.tenants.get("m").executor._hot)
        for _ in range(5):
            for n in (1, 2, 4):
                srv.submit("m", {"x": rng.normal(size=(n, 8)).astype(
                    np.float32)}).result(timeout=60)
        assert reg.get("executor.traces").value() == traces0
        # the buckets keep distinct pinned hot slots, none evicted another
        assert len(srv.tenants.get("m").executor._hot) == hot0 == 3


def test_submit_validation_and_error_propagation():
    main, y, scope = _mlp_tenant()
    with Server(bucket_edges=(1, 2), max_wait_ms=0.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        with pytest.raises(ValueError):  # wrong feed names
            srv.submit("m", {"wrong": np.zeros((1, 8), np.float32)})
        with pytest.raises(ValueError):  # rows > largest bucket
            srv.submit("m", {"x": np.zeros((3, 8), np.float32)})
        with pytest.raises(ValueError):  # scalar feed
            srv.submit("m", {"x": np.float32(1.0)})
        with pytest.raises(NotFoundError):
            srv.submit("nope", {"x": np.zeros((1, 8), np.float32)})
        # an executor failure surfaces on the FUTURE, not the dispatcher:
        # same feed name, wrong trailing shape compiles into a shape error
        fut = srv.submit("m", {"x": np.zeros((1, 5), np.float32)})
        with pytest.raises(Exception):
            fut.result(timeout=60)
        # ...and the server keeps serving afterwards
        out = srv.submit("m", {"x": np.zeros((1, 8), np.float32)}).result(
            timeout=60)[0]
        assert out.shape == (1, 4)


def test_closed_server_rejects_and_drains():
    main, y, scope = _mlp_tenant()
    srv = Server(bucket_edges=(1,), max_wait_ms=0.0)
    srv.add_tenant("m", main, ["x"], [y], scope)
    srv.start()
    fut = srv.submit("m", {"x": np.zeros((1, 8), np.float32)})
    srv.close()  # drain=True: queued work completes
    assert fut.result(timeout=60)[0].shape == (1, 4)
    with pytest.raises(AdmissionError):
        srv.submit("m", {"x": np.zeros((1, 8), np.float32)})


# ---------------------------------------------------------------------------
# tenancy: LRU eviction, recompile on return, quotas
# ---------------------------------------------------------------------------
def test_tenant_lru_eviction_and_recompile_on_return():
    reg = monitor.default_registry()
    tenants = [(f"t{i}",) + _mlp_tenant(seed=i) for i in range(3)]
    with Server(bucket_edges=(1,), max_wait_ms=0.0,
                max_live_programs=2) as srv:
        for name, main, y, scope in tenants:
            srv.add_tenant(name, main, ["x"], [y], scope)
        x = np.ones((1, 8), np.float32)
        ev0 = reg.get("serve.program_evictions").value(tenant="t0")
        out0 = srv.submit("t0", {"x": x}).result(timeout=60)[0]
        srv.submit("t1", {"x": x}).result(timeout=60)
        assert srv.tenants.live() == ["t0", "t1"]
        assert len(srv.tenants.get("t0").executor._cache) == 1
        # t2 arrives -> LRU victim t0 is evicted: compiled state dropped,
        # flight-recorded, counted
        srv.submit("t2", {"x": x}).result(timeout=60)
        assert srv.tenants.live() == ["t1", "t2"]
        assert len(srv.tenants.get("t0").executor._cache) == 0
        assert len(srv.tenants.get("t0").executor._hot) == 0
        assert (reg.get("serve.program_evictions").value(tenant="t0")
                == ev0 + 1)
        events = [e for e in trace.flight_recorder().events()
                  if e.get("kind") == "serve_program_evicted"
                  and e.get("name") == "t0"]
        assert events, "eviction was not flight-recorded"
        # t0 returns: transparently recompiles, same bits, evicts t1 (LRU)
        miss0 = reg.get("executor.cache_miss").value()
        out0b = srv.submit("t0", {"x": x}).result(timeout=60)[0]
        assert reg.get("executor.cache_miss").value() == miss0 + 1
        assert _bitwise_equal(out0, out0b)
        assert srv.tenants.live() == ["t2", "t0"]


def test_tenant_isolation_distinct_params():
    main_a, y_a, scope_a = _mlp_tenant(seed=1)
    main_b, y_b, scope_b = _mlp_tenant(seed=2)
    x = np.ones((1, 8), np.float32)
    with Server(bucket_edges=(1,), max_wait_ms=0.0) as srv:
        srv.add_tenant("a", main_a, ["x"], [y_a], scope_a)
        srv.add_tenant("b", main_b, ["x"], [y_b], scope_b)
        oa = srv.submit("a", {"x": x}).result(timeout=60)[0]
        ob = srv.submit("b", {"x": x}).result(timeout=60)[0]
    assert not np.array_equal(oa, ob)  # different seeds, different params


def test_per_tenant_quota_sheds_typed_error():
    main, y, scope = _mlp_tenant()
    srv = Server(bucket_edges=(1,), max_wait_ms=0.0)
    srv.add_tenant("m", main, ["x"], [y], scope, quota=2)
    # server NOT started: submits queue up and hold quota
    f1 = srv.submit("m", {"x": np.zeros((1, 8), np.float32)})
    f2 = srv.submit("m", {"x": np.zeros((1, 8), np.float32)})
    with pytest.raises(QuotaExceededError):
        srv.submit("m", {"x": np.zeros((1, 8), np.float32)})
    reg = monitor.default_registry()
    assert reg.get("serve.load_shed").value(reason="quota") >= 1
    srv.start()  # dispatcher drains the two queued requests
    assert f1.result(timeout=60) and f2.result(timeout=60)
    # quota released on completion — a new submit is admitted again
    assert srv.submit("m", {"x": np.zeros((1, 8), np.float32)}).result(
        timeout=60)
    srv.close()


# ---------------------------------------------------------------------------
# SLO admission
# ---------------------------------------------------------------------------
def test_slo_policy_projection_and_shed():
    slo = SLOPolicy(p99_ms=None, min_samples=5)
    for _ in range(10):
        slo.observe("t", "4", 10.0)
    p99 = slo.observed_p99("t")
    assert 9.0 <= p99 <= 11.0
    # disabled policy admits anything
    slo.admit("t", queue_depth=1000, max_batch=4)
    slo.p99_ms = 15.0
    slo.admit("t", queue_depth=0, max_batch=4)  # projection ~=p99 < 15
    with pytest.raises(AdmissionError):
        # 4 full dispatches queued ahead -> projected ~5x observed p99
        slo.admit("t", queue_depth=16, max_batch=4)
    reg = monitor.default_registry()
    assert reg.get("serve.load_shed").value(reason="slo") >= 1


def test_slo_policy_needs_min_samples():
    slo = SLOPolicy(p99_ms=0.001, min_samples=50)
    for _ in range(10):
        slo.observe("t", "1", 99.0)
    # immature cell: no shed even though observations dwarf the SLO
    slo.admit("t", queue_depth=100, max_batch=1)


def test_server_load_shed_end_to_end():
    main, y, scope = _mlp_tenant()
    slo = SLOPolicy(p99_ms=0.5, min_samples=1)
    srv = Server(bucket_edges=(1,), max_wait_ms=0.0, slo=slo)
    srv.add_tenant("mshed", main, ["x"], [y], scope)
    # no mature latency data -> first submit admitted (server not started,
    # so it just queues)
    fut = srv.submit("mshed", {"x": np.zeros((1, 8), np.float32)})
    # now the observed p99 dwarfs the SLO -> the next submit sheds
    for _ in range(5):
        slo.observe("mshed", "1", 50.0)
    with pytest.raises(AdmissionError):
        srv.submit("mshed", {"x": np.zeros((1, 8), np.float32)})
    srv.close(drain=False)
    with pytest.raises(AdmissionError):
        fut.result(timeout=60)  # drain=False fails the queued future too


# ---------------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------------
def _toy(seed=5, max_len=24):
    return make_toy_lm(vocab=48, hidden=16, max_len=max_len, seed=seed)


def _sequential_reference(prompts, new_tokens, seed=5, max_len=24):
    step_fn, init_fn = _toy(seed, max_len)
    out = []
    for p in prompts:
        cb = ContinuousBatcher(step_fn, init_fn, num_slots=1,
                               max_len=max_len)
        out.append(cb.decode([p], max_new_tokens=new_tokens)[0])
    return out


def test_continuous_join_evict_mid_decode_parity():
    step_fn, init_fn = _toy()
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=3, max_len=24)
    h1 = cb.join([1, 2, 3], max_new_tokens=8)
    h2 = cb.join([4, 5], max_new_tokens=8)
    for _ in range(4):
        cb.step()
    h3 = cb.join([7, 8, 9, 10], max_new_tokens=8)  # joins mid-decode
    for _ in range(3):
        cb.step()
    cb.evict(h2)  # evicted mid-decode: keeps partial output
    assert h2.done and h2.evicted
    partial = list(h2.tokens)
    assert 0 < len(partial) < 8
    cb.run_until_idle()
    assert h1.done and h3.done and not h1.evicted
    ref = _sequential_reference([[1, 2, 3], [4, 5], [7, 8, 9, 10]], 8)
    assert h1.tokens == ref[0]
    assert partial == ref[1][:len(partial)]  # prefix parity up to eviction
    assert h3.tokens == ref[2]


def test_continuous_decode_parity_many_sequences():
    prompts = [[(3 * i + j) % 48 for j in range(1 + i % 6)]
               for i in range(12)]
    step_fn, init_fn = _toy()
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=4, max_len=24)
    multi = cb.decode(prompts, max_new_tokens=10)
    assert multi == _sequential_reference(prompts, 10)


def test_continuous_zero_retraces_across_join_evict():
    reg = monitor.default_registry()
    step_fn, init_fn = _toy()
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=4, max_len=24)
    cb.decode([[1, 2]], max_new_tokens=4)  # warm: one trace
    traces0 = reg.get("executor.traces").value()
    h = cb.join([3, 4, 5], max_new_tokens=12)
    cb.step()
    cb.join([6], max_new_tokens=6)
    cb.step()
    cb.evict(h)
    cb.run_until_idle()
    cb.decode([[7, 8], [9]], max_new_tokens=8)
    assert reg.get("executor.traces").value() == traces0


def test_continuous_admission_and_bounds():
    step_fn, init_fn = _toy()
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=2, max_len=24)
    cb.join([1], max_new_tokens=4)
    cb.join([2], max_new_tokens=4)
    with pytest.raises(AdmissionError):
        cb.join([3], max_new_tokens=4)
    with pytest.raises(ValueError):  # prompt + new tokens > max_len
        ContinuousBatcher(step_fn, init_fn, num_slots=1, max_len=8).join(
            [1, 2, 3, 4, 5], max_new_tokens=8)
    with pytest.raises(ValueError):
        cb.join([], max_new_tokens=4)


# ---------------------------------------------------------------------------
# capi worker: legacy + pipelined PDID framing
# ---------------------------------------------------------------------------
def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = ROOT + (os.pathsep + existing if existing else "")
    return env


_WIRE_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64, 3: np.float64}
_WIRE_CODES = {np.dtype(v): k for k, v in _WIRE_DTYPES.items()}


def _enc_req(feed):
    out = b"PDRQ" + struct.pack("<i", len(feed))
    for name, arr in feed.items():
        nb = name.encode()
        out += struct.pack("<i", len(nb)) + nb
        out += struct.pack("<ii", _WIRE_CODES[arr.dtype], arr.ndim)
        out += struct.pack(f"<{arr.ndim}q", *arr.shape)
        out += arr.tobytes()
    return out


class _WorkerClient:
    def __init__(self, model_dir):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.inference.capi_worker",
             model_dir], stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=_child_env())
        assert self._rd(4) == b"PDOK"

    def _rd(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.proc.stdout.read(n - len(buf))
            assert chunk, "worker EOF"
            buf += chunk
        return buf

    def send(self, feed, req_id=None):
        frame = _enc_req(feed)
        if req_id is not None:
            frame = b"PDID" + struct.pack("<Q", req_id) + frame
        self.proc.stdin.write(frame)
        self.proc.stdin.flush()

    def read_response(self):
        magic, rid = self._rd(4), None
        if magic == b"PDID":
            (rid,) = struct.unpack("<Q", self._rd(8))
            magic = self._rd(4)
        if magic == b"PDER":
            (n,) = struct.unpack("<i", self._rd(4))
            return rid, RuntimeError(self._rd(n).decode())
        assert magic == b"PDRS", magic
        (n,) = struct.unpack("<i", self._rd(4))
        outs = {}
        for _ in range(n):
            (nl,) = struct.unpack("<i", self._rd(4))
            name = self._rd(nl).decode()
            code, ndim = struct.unpack("<ii", self._rd(8))
            dims = struct.unpack(f"<{ndim}q", self._rd(8 * ndim))
            dt = np.dtype(_WIRE_DTYPES[code])
            raw = self._rd(int(np.prod(dims)) * dt.itemsize)
            outs[name] = np.frombuffer(raw, dt).reshape(dims)
        return rid, outs

    def close(self):
        self.proc.stdin.close()
        self.proc.wait(timeout=60)


@pytest.fixture(scope="module")
def _capi_model(tmp_path_factory):
    # int32 elementwise model (x*x + x): results are exact, so bitwise
    # assertions hold under ANY XLA flag set the child inherits (the f32
    # wire path is covered by tests/test_capi.py, f32 padding parity by
    # the subprocess test above)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [6], dtype="int32")
        y = L.elementwise_add(L.elementwise_mul(x, x), x)
    exe = static.Executor()
    exe.run(startup)
    model_dir = str(tmp_path_factory.mktemp("serve_capi") / "m")
    static.save_inference_model(model_dir, ["x"], [y], exe,
                                main_program=main)
    return model_dir


def test_capi_worker_legacy_framing_unchanged(_capi_model):
    client = _WorkerClient(_capi_model)
    try:
        rng = np.random.default_rng(0)
        for _ in range(3):
            x = rng.integers(-50, 50, size=(2, 6)).astype(np.int32)
            client.send({"x": x})
            rid, outs = client.read_response()
            assert rid is None  # legacy responses carry no id frame
            assert _bitwise_equal(list(outs.values())[0], x * x + x)
    finally:
        client.close()


def test_capi_worker_pipelined_id_framing(_capi_model):
    client = _WorkerClient(_capi_model)
    try:
        rng = np.random.default_rng(1)
        xs = {i: rng.integers(-50, 50, size=(1, 6)).astype(np.int32)
              for i in range(8)}
        for i in range(8):  # pipeline: no waiting between sends
            client.send({"x": xs[i]}, req_id=i)
        got = {}
        for _ in range(8):
            rid, outs = client.read_response()
            assert rid is not None
            got[rid] = list(outs.values())[0]
        assert sorted(got) == list(range(8))
        for i, x in xs.items():
            assert _bitwise_equal(got[i], x * x + x)
        # id-less request after id'd traffic = drain barrier + strict order
        xl = rng.integers(-50, 50, size=(3, 6)).astype(np.int32)
        client.send({"x": xl})
        rid, outs = client.read_response()
        assert rid is None
        assert _bitwise_equal(list(outs.values())[0], xl * xl + xl)
    finally:
        client.close()


def test_capi_inproc_echoes_id_frame(_capi_model):
    from paddle_tpu.inference import capi_inproc

    h = capi_inproc.create(_capi_model)
    try:
        x = np.ones((1, 6), np.int32)
        resp = capi_inproc.run(h, b"PDID" + struct.pack("<Q", 77)
                               + _enc_req({"x": x}))
        assert resp[:4] == b"PDID"
        (rid,) = struct.unpack("<Q", resp[4:12])
        assert rid == 77 and resp[12:16] == b"PDRS"
        # id-less stays byte-compatible
        resp2 = capi_inproc.run(h, _enc_req({"x": x}))
        assert resp2[:4] == b"PDRS"
        assert resp[16:] == resp2[4:]
    finally:
        capi_inproc.destroy(h)


# ---------------------------------------------------------------------------
# servebench rides tier-1 through its self-check
# ---------------------------------------------------------------------------
def test_servebench_selfcheck():
    out = subprocess.run(
        [sys.executable, "-m", "tools.servebench", "--selfcheck"],
        cwd=ROOT, env=_child_env(), capture_output=True, text=True,
        timeout=570)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "servebench selfcheck: OK" in out.stdout


# ---------------------------------------------------------------------------
# per-request TTFT decomposition: queue / batch / compile / execute
# ---------------------------------------------------------------------------
def test_ttft_decomposition_histograms_and_flight_spans():
    from paddle_tpu.serving import slo

    reg = monitor.default_registry()
    fr = trace.flight_recorder()

    def counts():
        return {n: reg.get(n).count()
                for n in ("serve.ttft_queue_ms", "serve.ttft_batch_ms",
                          "serve.ttft_compile_ms", "serve.ttft_execute_ms")}

    main, y, scope = _mlp_tenant()
    c0 = counts()
    seq0 = fr.last_seq
    with Server(bucket_edges=(1, 2, 4), max_wait_ms=0.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        # cold request: pays the bucket compile
        srv.submit("m", {"x": np.ones((1, 8), np.float32)}).result(timeout=60)
        # hot request, same bucket: compile segment must be 0
        srv.submit("m", {"x": np.ones((1, 8), np.float32)}).result(timeout=60)
    c1 = counts()
    assert all(c1[n] - c0[n] == 2 for n in c1), (c0, c1)

    evs = fr.events_since(seq0)
    reqs = [e for e in evs if e["kind"] == "serve_request"]
    assert len(reqs) == 2
    cold, hot = reqs
    # every request carries the full decomposition + its own trace context
    for r in reqs:
        assert {"queue_ms", "batch_ms", "compile_ms", "execute_ms",
                "total_ms", "trace_id", "span_id"} <= set(r)
        assert r["total_ms"] >= r["execute_ms"] >= 0.0
    assert cold["compile_ms"] > 0.0          # first b1 dispatch compiled
    assert hot["compile_ms"] == 0.0          # hot cache: pure execute
    assert hot["execute_ms"] > 0.0
    # the dispatch span tree is in the ring for tracecat: dispatch parents
    # assemble + execute, and itself parents under the request context
    begins = [e for e in evs if e["kind"] == "span_begin"]
    assert {"serve::dispatch", "serve::batch_assemble",
            "serve::execute"} <= {e["name"] for e in begins}
    # each dispatch parents under ITS head request's context (cold and hot
    # were separate single-request batches)
    dispatch, = [e for e in begins if e["name"] == "serve::dispatch"
                 and e.get("parent_id") == cold["span_id"]]
    assert dispatch["trace_id"] == cold["trace_id"]
    execute, = [e for e in begins if e["name"] == "serve::execute"
                and e.get("parent_id") == dispatch["span_id"]]
    assert execute["trace_id"] == cold["trace_id"]
    assert any(e["name"] == "serve::dispatch"
               and e.get("parent_id") == hot["span_id"] for e in begins)
    # histograms agree with the flight attribution: compile seen once
    assert reg.get("serve.ttft_compile_ms").sum() >= cold["compile_ms"] - 1.0
    # the percentile gauges are live now (real numbers, not nan)
    assert not np.isnan(slo.TTFT_P50.value())
    assert not np.isnan(slo.TTFT_P99.value())


def test_submit_inside_span_parents_request_context():
    main, y, scope = _mlp_tenant()
    fr = trace.flight_recorder()
    seq0 = fr.last_seq
    with Server(bucket_edges=(1,), max_wait_ms=0.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)
        with trace.span("client::call") as sp:
            srv.submit("m", {"x": np.ones((1, 8), np.float32)}
                       ).result(timeout=60)
            client_ctx = sp.context
    req, = [e for e in fr.events_since(seq0) if e["kind"] == "serve_request"]
    # the request context is a child of the caller's span: same trace,
    # parented under it — tracecat stitches client -> server causality
    assert req["trace_id"] == client_ctx.trace_id
    assert req["parent_id"] == client_ctx.span_id


# ---------------------------------------------------------------------------
# slow stress variants (excluded from tier-1; run with `-m slow`)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_stress_many_threads_sustained():
    main, y, scope = _mlp_tenant()
    rng = np.random.default_rng(4)
    errs = []
    with Server(bucket_edges=(1, 2, 4, 8, 16), max_wait_ms=1.0) as srv:
        srv.add_tenant("m", main, ["x"], [y], scope)

        def client():
            try:
                for _ in range(200):
                    n = int(rng.integers(1, 5))
                    srv.submit("m", {"x": np.ones((n, 8), np.float32)}
                               ).result(timeout=120)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs, errs


@pytest.mark.slow
def test_stress_continuous_churn_parity():
    step_fn, init_fn = _toy(max_len=40)
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=6, max_len=40)
    prompts = [[(5 * i + j) % 48 for j in range(1 + i % 8)]
               for i in range(64)]
    multi = cb.decode(prompts, max_new_tokens=16)
    assert multi == _sequential_reference(prompts, 16, max_len=40)


def test_projected_p99_gauge_tracks_queue_backlog():
    """serve.projected_p99_ms{tenant} is a collect-time function gauge over
    SLOPolicy.projected_p99: equal to the observed p99 on an empty queue,
    inflated by the queued-dispatch factor under backlog."""
    slo = SLOPolicy(p99_ms=None, min_samples=1)
    depth = {"n": 0}
    slo.bind_queue(lambda: depth["n"], 8)
    for _ in range(20):
        slo.observe("t_proj", "4", 10.0)
    gauge = monitor.default_registry().get("serve.projected_p99_ms")
    observed = slo.observed_p99("t_proj")
    assert 9.0 <= observed <= 11.0
    # empty queue: the projection IS the observed p99
    assert gauge.value(tenant="t_proj") == pytest.approx(observed)
    # backlog: 64 queued rows / max_batch 8 -> 8 full dispatches ahead
    depth["n"] = 64
    assert gauge.value(tenant="t_proj") == pytest.approx(observed * 9.0)
    assert slo.projected_p99("t_proj", 64, 8) == \
        pytest.approx(observed * 9.0)
    # the gauge rides the normal exposition (history sampler's food)
    labels = dict(
        next(labels for labels, _ in gauge.samples()
             if labels.get("tenant") == "t_proj"))
    assert labels == {"tenant": "t_proj"}


def test_frontend_binds_projection_to_live_queue_depth():
    """Server wires its own queue into the policy at construction, so the
    exported projection reflects real backlog without any polling."""
    main, y, scope = _mlp_tenant()
    slo = SLOPolicy(p99_ms=None, min_samples=1)
    srv = Server(bucket_edges=(1,), max_wait_ms=0.0, slo=slo)
    srv.add_tenant("t_bind", main, ["x"], [y], scope)
    gauge = monitor.default_registry().get("serve.projected_p99_ms")
    # server NOT started: submits queue up and hold queued rows
    f1 = srv.submit("t_bind", {"x": np.zeros((1, 8), np.float32)})
    f2 = srv.submit("t_bind", {"x": np.zeros((1, 8), np.float32)})
    slo.observe("t_bind", "1", 10.0)
    backlog = gauge.value(tenant="t_bind")
    assert backlog == pytest.approx(
        slo.projected_p99("t_bind", 2, srv.max_batch))
    assert backlog > slo.observed_p99("t_bind")
    srv.start()                       # drain; projection falls back to p99
    assert f1.result(timeout=60) and f2.result(timeout=60)
    assert gauge.value(tenant="t_bind") == \
        pytest.approx(slo.observed_p99("t_bind"))
    srv.close()
