"""Optimizer math tests against hand-computed references (analogue of
unittests/test_sgd_op.py, test_adam_op.py, test_momentum_op.py ...)."""
import numpy as np
import pytest

import paddle_tpu as pd
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import (
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    AdamW,
    Adamax,
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
    Lamb,
    LarsMomentum,
    Momentum,
    RMSProp,
    lr as lr_sched,
)


def _np(x):
    return np.asarray(x)


def run_steps(opt, p0, grads_seq):
    params = [pd.to_tensor(p0)]
    state = opt.init(params)
    for g in grads_seq:
        params, state = opt.update([pd.to_tensor(g)], state, params)
    return _np(params[0])


class TestOptimizerMath:
    def test_sgd(self):
        p = np.array([1.0, 2.0], np.float32)
        g = np.array([0.5, -0.5], np.float32)
        out = run_steps(SGD(learning_rate=0.1), p, [g])
        np.testing.assert_allclose(out, p - 0.1 * g, rtol=1e-6)

    def test_momentum_two_steps(self):
        p = np.array([1.0], np.float32)
        g = np.array([1.0], np.float32)
        out = run_steps(Momentum(learning_rate=0.1, momentum=0.9), p, [g, g])
        # v1=1, p1=1-0.1; v2=0.9+1=1.9, p2=p1-0.19
        np.testing.assert_allclose(out, [1 - 0.1 - 0.19], rtol=1e-5)

    def test_nesterov_momentum(self):
        p = np.array([1.0], np.float32)
        g = np.array([1.0], np.float32)
        out = run_steps(Momentum(learning_rate=0.1, momentum=0.9,
                                 use_nesterov=True), p, [g])
        np.testing.assert_allclose(out, [1 - 0.1 * (1 + 0.9)], rtol=1e-5)

    def test_adam_first_step_equals_lr(self):
        # with bias correction, |update_1| == lr regardless of grad scale
        p = np.array([1.0], np.float32)
        out = run_steps(Adam(learning_rate=0.01, epsilon=1e-12), p,
                        [np.array([123.0], np.float32)])
        np.testing.assert_allclose(out, [1.0 - 0.01], rtol=1e-4)

    def test_adam_matches_manual_two_steps(self):
        lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
        p = np.array([0.5], np.float64)
        m = v = 0.0
        grads = [np.array([0.3]), np.array([-0.2])]
        pp = p.copy()
        for t, g in enumerate(grads, 1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mh = m / (1 - b1 ** t)
            vh = v / (1 - b2 ** t)
            pp = pp - lr * mh / (np.sqrt(vh) + eps)
        out = run_steps(Adam(learning_rate=lr), np.array([0.5], np.float32),
                        [g.astype(np.float32) for g in grads])
        np.testing.assert_allclose(out, pp, rtol=1e-4)

    def test_adamw_decoupled_decay(self):
        p = np.array([1.0], np.float32)
        g = np.array([0.0], np.float32)
        out = run_steps(AdamW(learning_rate=0.1, weight_decay=0.5), p, [g])
        # zero grad -> pure decay: p - lr*wd*p
        np.testing.assert_allclose(out, [1.0 - 0.1 * 0.5], rtol=1e-5)

    def test_adagrad(self):
        p = np.array([1.0], np.float32)
        g = np.array([2.0], np.float32)
        out = run_steps(Adagrad(learning_rate=0.1, epsilon=1e-6), p, [g])
        np.testing.assert_allclose(out, [1 - 0.1 * 2 / (2 + 1e-6)], rtol=1e-5)

    def test_rmsprop(self):
        p = np.array([1.0], np.float32)
        g = np.array([1.0], np.float32)
        out = run_steps(RMSProp(learning_rate=0.1, rho=0.9, epsilon=1e-6), p, [g])
        ms = 0.1 * 1.0
        np.testing.assert_allclose(out, [1 - 0.1 / np.sqrt(ms + 1e-6)], rtol=1e-4)

    def test_lamb_trust_ratio(self):
        p = np.array([3.0, 4.0], np.float32)  # norm 5
        g = np.array([0.1, 0.1], np.float32)
        out = run_steps(Lamb(learning_rate=0.01, lamb_weight_decay=0.0), p, [g])
        assert np.all(np.isfinite(out)) and np.all(out < p)

    def test_lars(self):
        p = np.ones([4], np.float32)
        g = np.full([4], 0.5, np.float32)
        out = run_steps(LarsMomentum(learning_rate=0.1, momentum=0.9), p, [g])
        assert np.all(np.isfinite(out)) and np.all(out < p)

    def test_adadelta_adamax_finite(self):
        p = np.ones([3], np.float32)
        g = np.full([3], 0.2, np.float32)
        for opt in (Adadelta(learning_rate=1.0), Adamax(learning_rate=0.1)):
            out = run_steps(opt, p, [g, g, g])
            assert np.all(np.isfinite(out))
            assert np.all(out < p)

    def test_weight_decay_l2(self):
        p = np.array([2.0], np.float32)
        g = np.array([0.0], np.float32)
        out = run_steps(SGD(learning_rate=0.1, weight_decay=0.1), p, [g])
        np.testing.assert_allclose(out, [2.0 - 0.1 * 0.1 * 2.0], rtol=1e-5)


class TestStatefulFacade:
    def test_step_updates_layer_params(self):
        m = nn.Linear(2, 2, bias_attr=False)
        before = _np(m.weight.value).copy()
        opt = SGD(learning_rate=0.5, parameters=m.parameters())
        g = np.ones((2, 2), np.float32)
        opt.step([pd.to_tensor(g)])
        np.testing.assert_allclose(_np(m.weight.value), before - 0.5, rtol=1e-5)


class TestGradClip:
    def test_by_value(self):
        g = {"a": pd.to_tensor(np.array([-3.0, 0.5, 3.0], np.float32))}
        out = ClipGradByValue(1.0)(g)
        np.testing.assert_allclose(_np(out["a"]), [-1, 0.5, 1])

    def test_by_norm(self):
        g = {"a": pd.to_tensor(np.array([3.0, 4.0], np.float32))}  # norm 5
        out = ClipGradByNorm(1.0)(g)
        np.testing.assert_allclose(_np(out["a"]), [0.6, 0.8], rtol=1e-5)

    def test_by_global_norm(self):
        g = {"a": pd.to_tensor(np.array([3.0], np.float32)),
             "b": pd.to_tensor(np.array([4.0], np.float32))}
        out = ClipGradByGlobalNorm(1.0)(g)
        total = np.sqrt(_np(out["a"])[0] ** 2 + _np(out["b"])[0] ** 2)
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)

    def test_no_clip_when_small(self):
        g = {"a": pd.to_tensor(np.array([0.1], np.float32))}
        out = ClipGradByGlobalNorm(1.0)(g)
        np.testing.assert_allclose(_np(out["a"]), [0.1], rtol=1e-6)


class TestLRSchedulers:
    def test_noam_peak_at_warmup(self):
        s = lr_sched.NoamDecay(d_model=512, warmup_steps=100)
        vals = [float(s.get_lr_at(t)) for t in [1, 50, 100, 200, 1000]]
        assert vals[2] == max(vals)

    def test_exponential(self):
        s = lr_sched.ExponentialDecay(0.1, 0.5)
        np.testing.assert_allclose(float(s.get_lr_at(2)), 0.025, rtol=1e-5)

    def test_piecewise(self):
        s = lr_sched.PiecewiseDecay([10, 20], [1.0, 0.5, 0.1])
        assert float(s.get_lr_at(5)) == 1.0
        assert float(s.get_lr_at(15)) == 0.5
        assert float(s.get_lr_at(25)) == pytest.approx(0.1)

    def test_cosine(self):
        s = lr_sched.CosineAnnealingDecay(1.0, T_max=100)
        assert float(s.get_lr_at(0)) == pytest.approx(1.0)
        assert float(s.get_lr_at(100)) == pytest.approx(0.0, abs=1e-6)

    def test_linear_warmup_wrapping_scheduler(self):
        inner = lr_sched.ExponentialDecay(0.1, 0.9)
        s = lr_sched.LinearWarmup(inner, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        assert float(s.get_lr_at(5)) == pytest.approx(0.05)
        assert float(s.get_lr_at(10)) == pytest.approx(0.1)

    def test_scheduler_in_optimizer(self):
        sched = lr_sched.StepDecay(0.1, step_size=1, gamma=0.5)
        opt = SGD(learning_rate=sched)
        p = [pd.to_tensor(np.array([1.0], np.float32))]
        state = opt.init(p)
        g = [pd.to_tensor(np.array([1.0], np.float32))]
        # step 1 -> lr = 0.1*0.5^1 = 0.05 (step counts from 1)
        p1, state = opt.update(g, state, p)
        np.testing.assert_allclose(_np(p1[0]), [1.0 - 0.05], rtol=1e-5)

    def test_reduce_on_plateau(self):
        s = lr_sched.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert s.last_lr < 0.1
