"""Batch-6 static ops: the RCNN/FPN detection tail (see
static/ops_tail6.py per-op reference files)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from tests.test_ops_tail2 import _run_single_op

RNG = np.random.default_rng(66)


def _iou(a, b, off=0.0):
    ax1, ay1, ax2, ay2 = a
    bx1, by1, bx2, by2 = b
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1) + off)
    ih = max(0.0, min(ay2, by2) - max(ay1, by1) + off)
    inter = iw * ih
    ua = (ax2 - ax1 + off) * (ay2 - ay1 + off) \
        + (bx2 - bx1 + off) * (by2 - by1 + off) - inter
    return inter / max(ua, 1e-10)


# -- generate_proposals -------------------------------------------------------

def test_generate_proposals_basic():
    N, A, H, W = 1, 3, 4, 4
    M = A * H * W
    scores = RNG.uniform(0, 1, (N, A, H, W)).astype(np.float32)
    deltas = (RNG.normal(0, 0.1, (N, 4 * A, H, W))).astype(np.float32)
    # anchors tiled over the grid, (H, W, A, 4)
    base = np.array([[0, 0, 15, 15], [4, 4, 11, 11], [2, 2, 13, 13]],
                    np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            anchors[i, j] = base + np.array([j * 4, i * 4, j * 4, i * 4])
    variances = np.ones_like(anchors)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)

    rois, probs, num = _run_single_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": variances},
        {"pre_nms_topN": M, "post_nms_topN": 8, "nms_thresh": 0.7,
         "min_size": 1.0},
        out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"))
    n = int(num[0])
    assert 1 <= n <= 8
    # valid rois are inside the image and properly ordered corners
    v = rois[0, :n]
    assert (v[:, 0] <= v[:, 2]).all() and (v[:, 1] <= v[:, 3]).all()
    assert (v >= 0).all() and (v <= 63).all()
    # probs sorted descending over the valid prefix
    p = probs[0, :n, 0]
    assert (np.diff(p) <= 1e-6).all()
    # pad region zeroed
    np.testing.assert_allclose(rois[0, n:], 0)
    # kept boxes mutually below the NMS threshold
    for i in range(n):
        for j in range(i):
            assert _iou(v[i], v[j]) <= 0.7 + 1e-5


# -- rpn_target_assign --------------------------------------------------------

def test_rpn_target_assign_labels():
    import paddle_tpu

    paddle_tpu.seed(5)
    # anchors: 4 perfectly matching gt, 4 far away
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [40, 40, 50, 50], [60, 60, 70, 70],
                        [200, 200, 210, 210], [220, 220, 230, 230],
                        [240, 240, 250, 250], [260, 260, 270, 270]],
                       np.float32)
    gt = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    loc, score, lbl, tbox, gtidx, nfg, nsc = _run_single_op(
        "rpn_target_assign", {"Anchor": anchors, "GtBoxes": gt},
        {"rpn_batch_size_per_im": 6, "rpn_positive_overlap": 0.7,
         "rpn_negative_overlap": 0.3, "rpn_fg_fraction": 0.5,
         "use_random": False},
        out_slots=("LocationIndex", "ScoreIndex", "TargetLabel",
                   "TargetBBox", "MatchedGtIndex", "ForegroundNumber",
                   "ScoreNumber"))
    n_fg = int(nfg[0])
    assert n_fg == 2  # the two exact matches
    fg_anchors = set(loc[0, :n_fg].tolist())
    assert fg_anchors == {0, 1}
    # all sampled background anchors are non-overlapping ones (2..7)
    n_sc = int(nsc[0])
    sampled = score[0, :n_sc].tolist()
    bgs = [a for a in sampled if a not in fg_anchors]
    assert bgs and all(a >= 2 for a in bgs)
    # labels: 1 for fg slots, 0 elsewhere in the sampled prefix
    assert int(lbl[0].sum()) == n_fg
    # gt mapping of fg anchors: anchor 0 -> gt 0, anchor 1 -> gt 1, and
    # TargetBBox carries the MATCHED GT BOX COORDINATES (reference {-1,4})
    assert gtidx[0, :n_fg].tolist() == [0, 1]
    np.testing.assert_allclose(tbox[0, :n_fg], gt[0, :2])
    np.testing.assert_allclose(tbox[0, n_fg:], 0)


# -- matrix_nms ---------------------------------------------------------------

def _matrix_nms_oracle(boxes, scores, score_th, post_th, top_k,
                       use_gaussian, sigma):
    """Direct transcription of NMSMatrix (matrix_nms_op.cc)."""
    order = np.argsort(-scores, kind="stable")
    order = [i for i in order if scores[i] > score_th][:top_k]
    if not order:
        return [], []
    iou_max = [0.0]
    ious = {}
    for i in range(1, len(order)):
        mx = 0.0
        for j in range(i):
            iou = _iou(boxes[order[i]], boxes[order[j]])
            ious[(i, j)] = iou
            mx = max(mx, iou)
        iou_max.append(mx)
    sel, ds_out = [], []
    if scores[order[0]] > post_th:
        sel.append(order[0])
        ds_out.append(scores[order[0]])
    for i in range(1, len(order)):
        min_decay = 1.0
        for j in range(i):
            iou = ious[(i, j)]
            if use_gaussian:
                # ref matrix_nms_op.cc:83: MULTIPLY by sigma
                decay = np.exp((iou_max[j] ** 2 - iou ** 2) * sigma)
            else:
                decay = (1.0 - iou) / (1.0 - iou_max[j])
            min_decay = min(min_decay, decay)
        ds = min_decay * scores[order[i]]
        if ds > post_th:
            sel.append(order[i])
            ds_out.append(ds)
    return sel, ds_out


@pytest.mark.parametrize("use_gaussian", [False, True])
def test_matrix_nms_matches_reference_decay(use_gaussian):
    M, C = 12, 3
    boxes = np.zeros((1, M, 4), np.float32)
    ctr = RNG.uniform(10, 90, (M, 2))
    wh = RNG.uniform(8, 20, (M, 2))
    boxes[0, :, 0] = ctr[:, 0] - wh[:, 0]
    boxes[0, :, 1] = ctr[:, 1] - wh[:, 1]
    boxes[0, :, 2] = ctr[:, 0] + wh[:, 0]
    boxes[0, :, 3] = ctr[:, 1] + wh[:, 1]
    scores = RNG.uniform(0, 1, (1, C, M)).astype(np.float32)
    out, _, num = _run_single_op(
        "matrix_nms", {"BBoxes": boxes, "Scores": scores},
        {"score_threshold": 0.2, "post_threshold": 0.1, "nms_top_k": M,
         "keep_top_k": 20, "use_gaussian": use_gaussian,
         "gaussian_sigma": 2.0, "background_label": 0},
        out_slots=("Out", "Index", "RoisNum"))
    # oracle: classes 1..C-1, global sort by decayed score
    expect = []
    for c in range(1, C):
        sel, ds = _matrix_nms_oracle(boxes[0], scores[0, c], 0.2, 0.1, M,
                                     use_gaussian, 2.0)
        expect += [(float(d), c, tuple(boxes[0, i])) for i, d in
                   zip(sel, ds)]
    expect.sort(key=lambda t: -t[0])
    n = int(num[0])
    assert n == len(expect)
    got = out[0, :n]
    np.testing.assert_allclose(got[:, 1], [e[0] for e in expect],
                               rtol=1e-4)
    np.testing.assert_array_equal(got[:, 0].astype(int),
                                  [e[1] for e in expect])


# -- box_decoder_and_assign ---------------------------------------------------

def test_box_decoder_and_assign():
    R, C = 4, 3
    prior = np.array([[0, 0, 9, 9]] * R, np.float32)
    pvar = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    target = RNG.normal(0, 0.5, (R, C * 4)).astype(np.float32)
    score = RNG.uniform(0, 1, (R, C)).astype(np.float32)
    dec, assign = _run_single_op(
        "box_decoder_and_assign",
        {"PriorBox": prior, "PriorBoxVar": pvar, "TargetBox": target,
         "BoxScore": score}, {"box_clip": 4.135},
        out_slots=("DecodeBox", "OutputAssignBox"))
    # oracle for roi 0, class 1
    t = target.reshape(R, C, 4)
    pw = ph = 10.0          # x2 - x1 + 1 (the reference's +1 widths)
    pcx = pcy = 5.0         # x1 + w/2
    j = 1
    dw = min(pvar[2] * t[0, j, 2], 4.135)
    dh = min(pvar[3] * t[0, j, 3], 4.135)
    cx = pvar[0] * t[0, j, 0] * pw + pcx
    cy = pvar[1] * t[0, j, 1] * ph + pcy
    w, h = np.exp(dw) * pw, np.exp(dh) * ph
    expect = [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1]
    np.testing.assert_allclose(dec[0, 4 * j:4 * j + 4], expect, rtol=1e-5)
    # assign picks the argmax NON-background class's box
    best = np.argmax(score[:, 1:], axis=1) + 1
    for r in range(R):
        np.testing.assert_allclose(
            assign[r], dec[r, 4 * best[r]:4 * best[r] + 4], rtol=1e-5)


# -- FPN distribute / collect -------------------------------------------------

def test_distribute_and_collect_fpn_proposals():
    # rois sized to land on levels 2, 3, 4 (refer level 3 @ scale 224)
    rois = np.array([
        [0, 0, 112, 112],     # sqrt(area)=112 -> level 2
        [0, 0, 224, 224],     # level 3
        [0, 0, 448, 448],     # level 4
        [0, 0, 100, 125],     # ~112 -> level 2
    ], np.float32)
    outs = _run_single_op(
        "distribute_fpn_proposals", {"FpnRois": rois},
        {"min_level": 2, "max_level": 4, "refer_level": 3,
         "refer_scale": 224},
        out_slots=("MultiFpnRois", "MultiLevelRoIsNum", "RestoreIndex"),
        n_out={"MultiFpnRois": 3, "MultiLevelRoIsNum": 1,
               "RestoreIndex": 1})
    l2, l3, l4, counts, restore = outs
    np.testing.assert_array_equal(counts, [2, 1, 1])
    np.testing.assert_allclose(l2[:2], rois[[0, 3]])
    np.testing.assert_allclose(l3[0], rois[1])
    np.testing.assert_allclose(l4[0], rois[2])
    # restore maps concatenated-by-level order back to the original
    np.testing.assert_array_equal(restore.ravel(), [0, 3, 1, 2])

    # collect: inverse with score-ordered top-k
    scores = [np.array([0.9, 0.5, 0, 0], np.float32),
              np.array([0.7, 0, 0, 0], np.float32),
              np.array([0.8, 0, 0, 0], np.float32)]
    rois_lvls = [l2, l3, l4]
    sel, num = _run_single_op(
        "collect_fpn_proposals",
        {"MultiLevelRois": rois_lvls, "MultiLevelScores": scores,
         "MultiLevelRoIsNum": np.array([2, 1, 1], np.int64)},
        {"post_nms_topN": 3}, out_slots=("FpnRois", "RoisNum"))
    assert int(np.asarray(num).ravel()[0]) == 3
    # top-3 by score: l2[0] (0.9), l4[0] (0.8), l3[0] (0.7)
    np.testing.assert_allclose(sel[0], rois[0])
    np.testing.assert_allclose(sel[1], rois[2])
    np.testing.assert_allclose(sel[2], rois[1])


def test_generate_proposals_min_size_respects_im_scale():
    """FilterBoxes contract: keep iff (x2-x1)/scale + 1 >= min_size —
    the +1 applies in ORIGINAL image space (review r05 regression)."""
    N, A, H, W = 1, 1, 1, 1
    scores = np.ones((N, A, H, W), np.float32)
    deltas = np.zeros((N, 4, H, W), np.float32)
    # anchor decodes to itself: width 4 px in scaled space
    anchors = np.array([[[[0, 0, 3, 3]]]], np.float32).reshape(1, 1, 1, 4)
    variances = np.ones_like(anchors)
    # scale 2.0: original width = 3/2 + 1 = 2.5 -> min_size 2 keeps it,
    # min_size 3 drops it
    im_info = np.array([[64.0, 64.0, 2.0]], np.float32)
    for ms, expect in ((2.0, 1), (3.0, 0)):
        _, _, num = _run_single_op(
            "generate_proposals",
            {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
             "Anchors": anchors, "Variances": variances},
            {"pre_nms_topN": 1, "post_nms_topN": 1, "nms_thresh": 0.7,
             "min_size": ms},
            out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"))
        assert int(num[0]) == expect, (ms, int(num[0]))


def test_matrix_nms_keep_top_k_minus_one_keeps_all():
    boxes = np.array([[[0, 0, 10, 10], [50, 50, 60, 60]]], np.float32)
    scores = np.array([[[0.9, 0.8], [0.7, 0.6]]], np.float32)  # C=2
    out, _, num = _run_single_op(
        "matrix_nms", {"BBoxes": boxes, "Scores": scores},
        {"score_threshold": 0.1, "post_threshold": 0.1, "nms_top_k": -1,
         "keep_top_k": -1, "background_label": -1},
        out_slots=("Out", "Index", "RoisNum"))
    assert int(num[0]) == 4  # both boxes for both classes survive
