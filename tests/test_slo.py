"""SLO engine (utils/slo.py + the monitor.py history layer): metrics
history rings, declarative objectives, multi-window burn-rate alerting,
and the fleet alert plane.

The acceptance contract pinned here:

* an injected 5x TTFT inflation drives the fast-window burn rate over
  threshold — the page alert goes pending -> firing within one
  evaluation interval, ``/healthz`` flips to 503, the flight ring holds
  the full transition chain, and the alert *resolves* after recovery
  (the short window aging out is what makes resolution possible);
* a 2-rank ``launch --telemetry_port`` job's per-rank ``/alerts`` legs
  dedupe into ONE job-level alert in ``tools/fleetview`` and ``--gate``
  exits non-zero while it fires;
* the engine is observation-only: zero steady-state retraces and warm
  persistent-cache starts hold with the ``slo`` flag on and the sampler
  running (the same pins the calibration ledger carries).

Everything else is deterministic-time unit coverage: the SeriesRing
cursor/truncation contract, counter-rate / gauge / histogram-delta
sampling, TOML/JSON objective files, burn-rate arithmetic, and the alert
state machine driven through ``engine.tick(now=...)``.
"""
import json
import os
import subprocess
import sys
import textwrap
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from paddle_tpu.core import flags
from paddle_tpu.utils import monitor, slo, telemetry, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_engine():
    """Each test gets its own singleton engine; the health provider a
    started engine registers must never leak a firing alert into another
    test's /healthz."""
    slo.reset()
    telemetry._health_providers.pop("slo", None)
    yield
    slo.reset()
    telemetry._health_providers.pop("slo", None)


@pytest.fixture
def _flags_guard():
    saved = flags.get_flags(["metrics", "slo", "slo_sample_secs",
                             "slo_objectives", "history_dir", "ledger",
                             "compile_cache_dir"])
    flags.set_flags({"metrics": True})
    yield
    flags.set_flags(saved)


def _get(port, path, timeout=10.0):
    """(status, json-or-text body) — reads error bodies too."""
    url = f"http://127.0.0.1:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            body = r.read().decode()
            status = r.status
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        status = e.code
    try:
        return status, json.loads(body)
    except ValueError:
        return status, body


# ---------------------------------------------------------------------------
# history layer: SeriesRing + MetricsHistory (monitor.py)
# ---------------------------------------------------------------------------

def test_series_ring_cursor_and_truncation():
    r = monitor.SeriesRing(capacity=4)
    for i in range(1, 5):
        r.append(i, float(i), float(i) * 10.0)
    items, truncated = r.read_since(0)
    assert [s[0] for s in items] == [1, 2, 3, 4]
    assert truncated is False
    r.append(5, 5.0, 50.0)                   # evicts seq 1
    items, truncated = r.read_since(0)
    assert [s[0] for s in items] == [2, 3, 4, 5]
    assert truncated is True                 # the cursor never saw seq 1
    # a cursor that already consumed the evicted sample is whole
    _, truncated = r.read_since(1)
    assert truncated is False
    items, truncated = r.read_since(5)
    assert items == [] and truncated is False
    # the evaluator's window read is by timestamp
    assert r.values_since_ts(4.0) == [40.0, 50.0]
    assert len(r) == 4


def test_series_key_rendering():
    assert monitor.series_key("t.x", {}) == "t.x"
    # keys sorted, whatever the insertion order
    assert monitor.series_key("t.x", {"b": "2", "a": "1"}) == "t.x{a=1,b=2}"


def test_history_counter_rate_and_aggregate(_flags_guard):
    hist = monitor.MetricsHistory()
    c = monitor.counter("t.slo_ctr", "", labelnames=("tenant",))
    c.inc(5, tenant="a")
    first = hist.sample(now=100.0)           # baseline tick: no rate yet
    assert "t.slo_ctr{tenant=a}:rate" not in first
    c.inc(10, tenant="a")
    c.inc(2, tenant="b")                     # new cell: baseline only
    out = hist.sample(now=102.0)
    assert out["t.slo_ctr{tenant=a}:rate"] == pytest.approx(5.0)
    assert "t.slo_ctr{tenant=b}:rate" not in out
    # the labeled family also lands an aggregate sum-rate under the bare key
    assert out["t.slo_ctr:rate"] == pytest.approx(5.0)
    c.inc(4, tenant="b")
    out = hist.sample(now=104.0)
    assert out["t.slo_ctr{tenant=b}:rate"] == pytest.approx(2.0)
    assert out["t.slo_ctr{tenant=a}:rate"] == 0.0    # idle cell: rate 0
    assert out["t.slo_ctr:rate"] == pytest.approx(2.0)


def test_history_gauge_skips_non_finite(_flags_guard):
    hist = monitor.MetricsHistory()
    g = monitor.gauge("t.slo_gauge", "")
    g.set(3.0)
    assert hist.sample(now=1.0)["t.slo_gauge"] == 3.0
    g.set(float("nan"))
    assert "t.slo_gauge" not in hist.sample(now=2.0)
    g.set(float("inf"))
    assert "t.slo_gauge" not in hist.sample(now=3.0)


def test_history_histogram_delta_percentiles_recover(_flags_guard):
    """The load-bearing property: percentiles come from inter-tick bucket
    DELTAS, so a latency spike ages out of the series as soon as healthy
    traffic resumes — a cumulative-cell percentile never recovers, and an
    alert on it would never resolve."""
    hist = monitor.MetricsHistory()
    h = monitor.histogram("t.slo_hist", "",
                          buckets=(5.0, 10.0, 25.0, 50.0, 100.0))
    for _ in range(50):
        h.observe(8.0)
    assert "t.slo_hist:p50" not in hist.sample(now=0.0)  # baseline tick
    for _ in range(50):
        h.observe(8.0)
    out = hist.sample(now=1.0)               # healthy: all deltas in (5,10]
    assert out["t.slo_hist:p50"] == pytest.approx(7.5)
    assert out["t.slo_hist:p99"] == pytest.approx(9.95)
    for _ in range(50):
        h.observe(80.0)                      # the spike: (50,100] bucket
    out = hist.sample(now=2.0)
    assert out["t.slo_hist:p50"] == pytest.approx(75.0)
    assert out["t.slo_hist:p99"] == pytest.approx(99.5)
    for _ in range(50):
        h.observe(8.0)                       # recovery
    out = hist.sample(now=3.0)
    assert out["t.slo_hist:p50"] == pytest.approx(7.5)   # spike aged out
    assert out["t.slo_hist:p99"] == pytest.approx(9.95)
    # a tick with no new observations emits nothing (not stale percentiles)
    out = hist.sample(now=4.0)
    assert "t.slo_hist:p50" not in out and "t.slo_hist:p99" not in out


def test_history_read_since_thinning_and_unknown_series(_flags_guard):
    reg = monitor.MetricRegistry()
    hist = monitor.MetricsHistory(reg, capacity=8)
    g = reg.gauge("t.slo_thin", "")
    for i in range(20):
        g.set(float(i))
        hist.sample(now=float(i))
    doc = hist.read_since("t.slo_thin", 0)
    assert doc["truncated"] is True          # ring kept only the last 8
    assert len(doc["samples"]) == 8
    assert [s[2] for s in doc["samples"]] == [float(i) for i in range(12, 20)]
    # read-time thinning always keeps the newest sample, never truncates
    thin = hist.read_since("t.slo_thin", 0, max_points=4)
    assert len(thin["samples"]) == 4
    assert thin["samples"][-1][2] == 19.0
    assert thin["last_seq"] == doc["last_seq"]
    # a cursor at the live head reads clean
    head = hist.read_since("t.slo_thin", doc["last_seq"])
    assert head["samples"] == [] and head["truncated"] is False
    assert hist.read_since("t.no_such_series", 0) == {
        "last_seq": 0, "truncated": False, "samples": []}


def test_history_max_series_backstop(_flags_guard):
    reg = monitor.MetricRegistry()
    for i in range(4):
        reg.gauge(f"t.slo_card_{i}", "").set(1.0)
    hist = monitor.MetricsHistory(reg, max_series=2)
    hist.sample(now=1.0)
    assert hist.names() == ["t.slo_card_0", "t.slo_card_1"]
    assert hist.dropped_series() == 2
    # existing series keep recording once the cap is hit
    before = hist.read_since("t.slo_card_0", 0)["last_seq"]
    hist.sample(now=2.0)
    assert hist.read_since("t.slo_card_0", 0)["last_seq"] > before


def test_history_priority_series_exempt_from_cap(_flags_guard):
    """A cardinality explosion must not starve the alerting plane: series
    under a priority prefix (the engine's own slo.* family + every
    objective's metric) get rings past max_series, up to the 2x ceiling."""
    reg = monitor.MetricRegistry()
    for i in range(4):
        reg.gauge(f"t.slo_noise_{i}", "").set(1.0)
    reg.gauge("t.slo_vip", "").set(7.0)       # sorts after the noise
    hist = monitor.MetricsHistory(reg, max_series=2)
    hist.set_priority_prefixes(("t.slo_vip",))
    hist.sample(now=1.0)
    assert "t.slo_vip" in hist.names()        # exempt from the cap
    assert hist.read_since("t.slo_vip", 0)["samples"][-1][2] == 7.0
    assert hist.dropped_series() == 2         # the noise still capped
    # the engine keeps the prefix set synced to its objective set
    eng = slo.SLOEngine(registry=reg)
    eng.register(slo.SLO("vip", "t.slo_vip", ">", 1e18,
                         windows=[slo.Window(0.2, 1.0, 1.0, "ticket")]))
    assert hist is not eng.history
    assert eng.history._priority == ("slo.", "t.slo_vip")
    eng.clear()
    assert eng.history._priority == ("slo.",)


def test_match_series_bare_and_labeled(_flags_guard):
    reg = monitor.MetricRegistry()
    hist = monitor.MetricsHistory(reg)
    reg.gauge("t.slo_match", "").set(1.0)
    reg.gauge("t.slo_match_lab", "", labelnames=("k",)).set(2.0, k="a")
    c = reg.counter("t.slo_match_ctr", "")
    c.inc(1)
    hist.sample(now=1.0)
    c.inc(1)
    hist.sample(now=2.0)
    assert hist.match_series("t.slo_match") == ["t.slo_match"]
    assert hist.match_series("t.slo_match_lab") == ["t.slo_match_lab{k=a}"]
    assert hist.match_series("t.slo_match_ctr", ":rate") == \
        ["t.slo_match_ctr:rate"]
    # a gauge lookup must not match another metric's labeled cells or a
    # counter's :rate series
    assert hist.match_series("t.slo_match_ctr") == []


# ---------------------------------------------------------------------------
# objectives: validation, defaults, TOML/JSON files
# ---------------------------------------------------------------------------

def test_window_and_slo_validation():
    w = slo.Window(300, 3600, 14.4)
    assert w.severity == "page"
    with pytest.raises(ValueError):
        slo.Window(3600, 300, 14.4)          # inverted pair
    with pytest.raises(ValueError):
        slo.Window(300, 3600, 0.0)           # burn must be > 0
    with pytest.raises(ValueError):
        slo.Window(0, 3600, 1.0)
    with pytest.raises(ValueError):
        slo.Window(300, 3600, 1.0, severity="sms")
    s = slo.SLO("x", "t.m", ">", 1.0, objective_pct=99.9)
    assert s.error_budget == pytest.approx(0.001)
    assert s.series_suffix == ""
    assert slo.SLO("x", "t.m", ">", 1.0, signal="p99").series_suffix == ":p99"
    with pytest.raises(ValueError):
        slo.SLO("", "t.m", ">", 1.0)
    with pytest.raises(ValueError):
        slo.SLO("x", "", ">", 1.0)
    with pytest.raises(ValueError):
        slo.SLO("x", "t.m", "!=", 1.0)
    with pytest.raises(ValueError):
        slo.SLO("x", "t.m", ">", 1.0, objective_pct=100.0)
    with pytest.raises(ValueError):
        slo.SLO("x", "t.m", ">", 1.0, signal="p75")
    with pytest.raises(ValueError):
        slo.SLO("x", "t.m", ">", 1.0, windows=[])
    with pytest.raises(TypeError):
        slo.SLO("x", "t.m", ">", 1.0, windows=[{"short_secs": 1}])
    # op is the VIOLATION comparator
    assert slo.SLO("x", "t.m", ">", 5.0).violates(6.0)
    assert not slo.SLO("x", "t.m", ">", 5.0).violates(5.0)
    assert slo.SLO("x", "t.m", "<", 5.0).violates(4.0)
    assert slo.SLO("x", "t.m", ">=", 5.0).violates(5.0)
    assert slo.SLO("x", "t.m", "<=", 5.0).violates(5.0)


def test_default_objectives_ship_complete():
    objectives = slo.default_objectives()
    assert [s.name for s in objectives] == [
        "serve-ttft-p99", "serve-load-shed", "train-goodput", "ledger-drift"]
    for s in objectives:
        assert s.windows == slo.DEFAULT_WINDOWS
        assert s.description
    # fresh instances every call: engines/tests can mutate freely
    assert slo.default_objectives()[0] is not objectives[0]
    # the shipped pairs are the SRE-workbook fast/slow standards
    (page, ticket) = slo.DEFAULT_WINDOWS
    assert page.severity == "page" and page.burn == 14.4
    assert ticket.severity == "ticket"
    assert page.short_secs < page.long_secs


def test_objective_file_toml_and_json(tmp_path):
    toml = tmp_path / "obj.toml"
    toml.write_text(textwrap.dedent("""
        # serving latency page
        [[slo]]
        name = "ttft"
        metric = "serve.ttft_p99_ms"
        op = ">"
        threshold = 500.0
        objective_pct = 99.5
        signal = "value"
        windows = [ { short_secs = 300, long_secs = 3600, burn = 14.4, severity = "page" }, { short_secs = 1800, long_secs = 21600, burn = 6.0, severity = "ticket" } ]

        [[slo]]
        name = "shed"
        metric = "serve.load_shed"
        op = ">"
        threshold = 0.0
        signal = "rate"
        description = "no shedding"
    """))
    loaded = slo.load_objectives(str(toml))
    assert [s.name for s in loaded] == ["ttft", "shed"]
    assert loaded[0].objective_pct == 99.5
    assert loaded[0].windows[0].burn == 14.4
    assert loaded[0].windows[1].severity == "ticket"
    assert loaded[1].windows == slo.DEFAULT_WINDOWS   # defaulted
    assert loaded[1].signal == "rate"

    js = tmp_path / "obj.json"
    js.write_text(json.dumps(
        {"slo": [s.to_json() for s in loaded]}))
    reloaded = slo.load_objectives(str(js))
    assert [s.to_json() for s in reloaded] == [s.to_json() for s in loaded]


def test_objective_file_rejections(tmp_path):
    with pytest.raises(ValueError, match="non-empty"):
        slo.parse_objectives({"nope": []})
    with pytest.raises(ValueError, match="unknown keys"):
        slo.parse_objectives({"slo": [{"name": "x", "metric": "t.m",
                                       "op": ">", "threshold": 1.0,
                                       "burn": 3}]})
    with pytest.raises(ValueError, match="duplicate"):
        slo.parse_objectives({"slo": [
            {"name": "x", "metric": "t.m", "op": ">", "threshold": 1.0},
            {"name": "x", "metric": "t.n", "op": ">", "threshold": 2.0}]})
    with pytest.raises(ValueError, match="finite"):
        slo.parse_objectives({"slo": [{"name": "x", "metric": "t.m",
                                       "op": ">"}]})   # threshold missing
    with pytest.raises(ValueError, match="windows"):
        slo.parse_objectives({"slo": [
            {"name": "x", "metric": "t.m", "op": ">", "threshold": 1.0,
             "windows": [{"short_secs": 3600, "long_secs": 300,
                          "burn": 1.0}]}]})
    bad = tmp_path / "bad.toml"
    bad.write_text("[[slo]]\nname = @@@\n")
    with pytest.raises(ValueError):
        slo.load_objectives(str(bad))


# ---------------------------------------------------------------------------
# the engine: burn-rate arithmetic + alert state machine, deterministic time
# ---------------------------------------------------------------------------

def _page_slo(name="t-bad", metric="t.slo_sm", short=2.0, long_=8.0,
              burn=1.5):
    return slo.SLO(name, metric, ">", 5.0, objective_pct=90.0,
                   windows=[slo.Window(short, long_, burn, "page")])


def test_burn_rate_math_and_state_machine(_flags_guard):
    eng = slo.SLOEngine()
    eng.register(_page_slo())
    g = monitor.gauge("t.slo_sm", "")
    fr = trace.flight_recorder()
    seq0 = fr.last_seq
    g.set(1.0)
    t = 100.0
    for i in range(10):                      # healthy minute: all ok
        eng.tick(now=t + i)
    doc = eng.alerts_doc()
    assert doc["firing"] == [] and doc["transitions"] == []
    ((_, sev),) = [(a["slo"], a["severity"]) for a in doc["alerts"]]
    assert sev == "page"
    reg = monitor.default_registry()
    assert reg.get("slo.burn_rate").value(slo="t-bad", window="2s") == 0.0

    g.set(50.0)                              # violation begins
    eng.tick(now=t + 10)
    # short window (>=108s): 1 bad of 3 -> 0.333/0.1 = 3.33 > 1.5, but the
    # long window (>=102s) is 1 of 9 -> 1.11 < 1.5: no alert on a blip
    st = {(a["slo"], a["severity"]): a["state"]
          for a in eng.alerts_doc()["alerts"]}
    assert st[("t-bad", "page")] == "ok"
    assert reg.get("slo.burn_rate").value(
        slo="t-bad", window="2s") == pytest.approx(1 / 3 / 0.1)
    eng.tick(now=t + 11)
    # sustained: short 2/3 -> 6.67, long 2/9 -> 2.22; both over threshold.
    # for_secs=0 -> pending and firing land on the SAME evaluation tick.
    doc = eng.alerts_doc()
    assert doc["firing"] == ["t-bad:page"]
    assert reg.get("slo.alerts_firing").value(slo="t-bad",
                                              severity="page") == 1.0
    assert eng.health()["healthy"] is False

    g.set(1.0)                               # recovery
    eng.tick(now=t + 12)                     # short window still has bads
    eng.tick(now=t + 14)                     # >=112s: all healthy -> resolve
    doc = eng.alerts_doc()
    assert doc["firing"] == []
    st = {(a["slo"], a["severity"]): a["state"] for a in doc["alerts"]}
    assert st[("t-bad", "page")] == "resolved"
    assert eng.health()["healthy"] is True
    assert reg.get("slo.alerts_firing").value(slo="t-bad",
                                              severity="page") == 0.0

    chain = [(tr["from"], tr["to"]) for tr in doc["transitions"]]
    assert chain == [("ok", "pending"), ("pending", "firing"),
                     ("firing", "resolved")]
    # every transition is flight-recorded with the burn rates that caused it
    events = [e for e in fr.events_since(seq0) if e["kind"] == "slo_alert"]
    assert [(e["from"], e["to"]) for e in events] == chain
    firing_ev = events[1]
    assert firing_ev["name"] == "t-bad:page"
    assert firing_ev["burn_short"] > firing_ev["burn_threshold"]
    assert firing_ev["burn_long"] > firing_ev["burn_threshold"]
    assert firing_ev["windows"] == [2.0, 8.0]
    assert reg.get("slo.evaluations").value() >= 14


def test_pending_confirmation_window(_flags_guard):
    """for_secs > 0 holds the alert in pending until the condition has
    been true that long; a blip that clears first goes back to ok."""
    eng = slo.SLOEngine(for_secs=3.0)
    eng.register(_page_slo(metric="t.slo_pend"))
    g = monitor.gauge("t.slo_pend", "")
    g.set(1.0)
    t = 200.0
    for i in range(10):
        eng.tick(now=t + i)
    g.set(50.0)
    eng.tick(now=t + 10)
    eng.tick(now=t + 11)                     # condition true -> pending
    st = {a["slo"]: a["state"] for a in eng.alerts_doc()["alerts"]}
    assert st["t-bad"] == "pending"
    g.set(1.0)                               # blip clears before for_secs
    eng.tick(now=t + 13)
    eng.tick(now=t + 15)
    st = {a["slo"]: a["state"] for a in eng.alerts_doc()["alerts"]}
    assert st["t-bad"] == "ok"               # never fired
    g.set(50.0)                              # sustained violation now
    eng.tick(now=t + 16)                     # pending (since=216)
    eng.tick(now=t + 17)
    eng.tick(now=t + 18)
    st = {a["slo"]: a["state"] for a in eng.alerts_doc()["alerts"]}
    assert st["t-bad"] == "pending"          # 2s held < 3s confirmation
    eng.tick(now=t + 21)                     # 4s held -> firing
    assert eng.alerts_doc()["firing"] == ["t-bad:page"]
    chain = [(tr["from"], tr["to"]) for tr in eng.alerts_doc()["transitions"]]
    assert chain == [("ok", "pending"), ("pending", "ok"),
                     ("ok", "pending"), ("pending", "firing")]


def test_worst_cell_of_labeled_family_pages(_flags_guard):
    """One bad tenant must page like all-bad traffic: cells are judged
    per series with the worst bad-fraction winning."""
    eng = slo.SLOEngine()
    eng.register(_page_slo(metric="t.slo_tenants"))
    g = monitor.gauge("t.slo_tenants", "", labelnames=("tenant",))
    g.set(1.0, tenant="good")
    g.set(50.0, tenant="bad")
    t = 300.0
    for i in range(10):
        eng.tick(now=t + i)
    assert eng.alerts_doc()["firing"] == ["t-bad:page"]


def test_load_default_objectives_resolution(tmp_path, _flags_guard):
    # 1. the slo_objectives file wins over the shipped defaults
    path = tmp_path / "obj.toml"
    path.write_text('[[slo]]\nname = "mine"\nmetric = "t.m"\nop = ">"\n'
                    'threshold = 1.0\n')
    flags.set_flags({"slo_objectives": str(path)})
    eng = slo.SLOEngine()
    eng.load_default_objectives()
    assert [s.name for s in eng.objectives()] == ["mine"]
    # 2. a broken file is flight-recorded and the defaults stand in
    bad = tmp_path / "bad.toml"
    bad.write_text("[[slo]]\nname = @@@\n")
    flags.set_flags({"slo_objectives": str(bad)})
    fr = trace.flight_recorder()
    seq0 = fr.last_seq
    eng2 = slo.SLOEngine()
    eng2.load_default_objectives()
    assert [s.name for s in eng2.objectives()] == \
        sorted(s.name for s in slo.default_objectives())
    errs = [e for e in fr.events_since(seq0)
            if e["kind"] == "slo_objectives_error"]
    assert errs and errs[0]["path"] == str(bad)
    # 3. code registration wins: load_default_objectives is then a no-op
    eng3 = slo.SLOEngine()
    eng3.register(_page_slo(name="coded"))
    eng3.load_default_objectives()
    assert [s.name for s in eng3.objectives()] == ["coded"]


def test_history_jsonl_mirror(tmp_path, _flags_guard, monkeypatch):
    flags.set_flags({"history_dir": str(tmp_path)})
    eng = slo.SLOEngine()
    eng._sink_path = slo._history_sink_path()
    assert eng._sink_path == str(tmp_path / "history.rank0.jsonl")
    g = monitor.gauge("t.slo_mirror", "")
    g.set(2.0)
    eng.tick(now=1.0)
    g.set(4.0)
    eng.tick(now=2.0)
    lines = [json.loads(l) for l in
             open(tmp_path / "history.rank0.jsonl", encoding="utf-8")]
    assert len(lines) == 2
    assert lines[0]["rank"] == 0 and lines[0]["ts"] == 1.0
    assert lines[0]["samples"]["t.slo_mirror"] == 2.0
    assert lines[1]["samples"]["t.slo_mirror"] == 4.0
    # env-var resolution (the launch --history_dir contract) when the flag
    # is unset; flag wins when both are set
    flags.set_flags({"history_dir": ""})
    env_dir = tmp_path / "env"
    monkeypatch.setenv(slo.HISTORY_DIR_ENV, str(env_dir))
    assert slo._history_sink_path() == str(env_dir / "history.rank0.jsonl")
    flags.set_flags({"history_dir": str(tmp_path)})
    assert slo._history_sink_path() == str(tmp_path / "history.rank0.jsonl")
    monkeypatch.delenv(slo.HISTORY_DIR_ENV)
    flags.set_flags({"history_dir": ""})
    assert slo._history_sink_path() is None


# ---------------------------------------------------------------------------
# the telemetry plane: /alerts and /history
# ---------------------------------------------------------------------------

def test_alerts_endpoint_without_and_with_engine(_flags_guard):
    srv = telemetry.TelemetryServer(port=0).start()
    try:
        # no engine singleton: an empty doc, never an implicit engine
        status, doc = _get(srv.port, "/alerts")
        assert status == 200
        assert doc["running"] is False and doc["alerts"] == []
        assert slo.get_engine() is None
        eng = slo.engine()
        eng.register(_page_slo(metric="t.slo_ep"))
        g = monitor.gauge("t.slo_ep", "")
        g.set(50.0)
        for i in range(10):
            eng.tick(now=400.0 + i)
        status, doc = _get(srv.port, "/alerts")
        assert status == 200
        assert doc["firing"] == ["t-bad:page"]
        (alert,) = doc["alerts"]
        assert alert["metric"] == "t.slo_ep" and alert["op"] == ">"
        assert doc["objectives"][0]["name"] == "t-bad"
        assert [(tr["from"], tr["to"]) for tr in doc["transitions"]] == \
            [("ok", "pending"), ("pending", "firing")]
    finally:
        srv.stop()


def test_history_endpoint_filter_cursor_and_400(_flags_guard):
    srv = telemetry.TelemetryServer(port=0).start()
    try:
        eng = slo.engine()
        # a (never-firing) objective marks the metric cap-exempt: the ring
        # must exist even when the suite-long registry is over max_series
        eng.register(slo.SLO("hep-pin", "t.slo_hep", ">", 1e18,
                             windows=[slo.Window(0.2, 1.0, 1.0, "ticket")]))
        g = monitor.gauge("t.slo_hep", "")
        for i in range(6):
            g.set(float(i))
            eng.tick(now=500.0 + i)
        status, doc = _get(srv.port, "/history")
        assert status == 200
        assert "t.slo_hep" in doc["names"]
        assert doc["sample_secs"] == float(flags.get_flag("slo_sample_secs"))
        samples = doc["series"]["t.slo_hep"]["samples"]
        assert [s[2] for s in samples] == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        # ?series= filters; unknown names are dropped, not 500s
        q = urllib.parse.quote("t.slo_hep,t.nope", safe=",")
        status, doc = _get(srv.port, f"/history?series={q}&max_points=3")
        assert status == 200
        assert list(doc["series"]) == ["t.slo_hep"]
        assert len(doc["series"]["t.slo_hep"]["samples"]) == 3
        assert doc["series"]["t.slo_hep"]["samples"][-1][2] == 5.0
        # cursor resume: since=last_seq of the series reads clean
        last = doc["series"]["t.slo_hep"]["last_seq"]
        status, doc = _get(srv.port, f"/history?series={q}&since={last}")
        assert doc["series"]["t.slo_hep"]["samples"] == []
        assert doc["series"]["t.slo_hep"]["truncated"] is False
        status, _ = _get(srv.port, "/history?since=zebra")
        assert status == 400
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# acceptance: injected 5x TTFT inflation pages, /healthz flips, resolves
# ---------------------------------------------------------------------------

def test_injected_ttft_inflation_pages_healthz_and_resolves(_flags_guard):
    from paddle_tpu.serving import slo as sslo

    srv = telemetry.TelemetryServer(port=0).start()
    eng = slo.engine()
    eng.register(slo.SLO(
        "ttft-page", "serve.ttft_ms", ">", 25.0, objective_pct=99.0,
        signal="p99", windows=[slo.Window(0.4, 1.6, 2.0, "page")]))
    fr = trace.flight_recorder()
    seq0 = fr.last_seq
    eng.start(sample_secs=0.05)
    try:
        # healthy phase: TTFT well under threshold
        deadline = time.time() + 1.0
        while time.time() < deadline:
            sslo.TTFT_MS.observe(10.0)
            time.sleep(0.01)
        _, doc = _get(srv.port, "/alerts")
        assert doc["running"] is True and doc["firing"] == []
        status, _ = _get(srv.port, "/healthz")
        assert status == 200

        # the injected degradation: 5x TTFT inflation
        fired = False
        deadline = time.time() + 15.0
        while time.time() < deadline:
            sslo.TTFT_MS.observe(50.0)
            time.sleep(0.01)
            _, doc = _get(srv.port, "/alerts")
            if "ttft-page:page" in doc["firing"]:
                fired = True
                break
        assert fired, "page alert never fired under 5x TTFT inflation"
        (alert,) = doc["alerts"]
        assert alert["burn_short"] > 2.0 and alert["burn_long"] > 2.0
        # a firing page flips /healthz to 503 through the provider hook
        status, hdoc = _get(srv.port, "/healthz")
        assert status == 503 and hdoc["status"] == "degraded"
        assert hdoc["slo"]["firing"] == ["ttft-page:page"]
        # the burn-rate series the evaluator exports is itself in /history
        q = urllib.parse.quote(
            "slo.burn_rate{slo=ttft-page,window=0.4s}", safe="")
        _, h = _get(srv.port, f"/history?series={q}")
        assert f"slo.burn_rate{{slo=ttft-page,window=0.4s}}" in h["names"]

        # recovery: healthy traffic ages the bads out of the short window
        resolved = False
        deadline = time.time() + 15.0
        while time.time() < deadline:
            sslo.TTFT_MS.observe(10.0)
            time.sleep(0.01)
            _, doc = _get(srv.port, "/alerts")
            states = {(a["slo"], a["severity"]): a["state"]
                      for a in doc["alerts"]}
            if states.get(("ttft-page", "page")) == "resolved":
                resolved = True
                break
        assert resolved, "page alert never resolved after recovery"
        status, _ = _get(srv.port, "/healthz")
        assert status == 200

        # the flight ring carries the whole transition chain, in order
        chain = [(e["from"], e["to"]) for e in fr.events_since(seq0)
                 if e["kind"] == "slo_alert"]
        assert chain.index(("ok", "pending")) \
            < chain.index(("pending", "firing")) \
            < chain.index(("firing", "resolved"))
    finally:
        eng.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# acceptance: 2-rank launch, fleetview dedupes the job alert, --gate trips
# ---------------------------------------------------------------------------

def _free_port_base():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_launch_two_ranks_fleetview_dedupes_and_gates(tmp_path):
    from paddle_tpu.distributed.launch import launch

    out = tmp_path / "out"
    out.mkdir()
    hist_dir = tmp_path / "hist"
    base = _free_port_base()
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import json, os, time
        import paddle_tpu  # bootstrap starts this rank's telemetry plane
        from paddle_tpu.utils import monitor, slo, telemetry

        OUT = {str(out)!r}
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        srv = telemetry.get_server()
        assert srv is not None and srv.port == {base} + rank, srv

        # every rank violates the SAME objective -> the job view must
        # dedupe the two per-rank alerts into one
        monitor.gauge("t.fleet_slo", "").set(99.0)
        eng = slo.engine()
        eng.register(slo.SLO("fleet-bad", "t.fleet_slo", ">", 5.0,
                             objective_pct=90.0,
                             windows=[slo.Window(0.3, 1.2, 1.0, "page")]))
        eng.start(sample_secs=0.05)

        deadline = time.time() + 30
        while time.time() < deadline:
            if "fleet-bad:page" in eng.alerts_doc()["firing"]:
                break
            time.sleep(0.05)
        else:
            raise SystemExit("alert never fired on rank %d" % rank)

        open(os.path.join(OUT, "ready.%d" % rank), "w").close()
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(os.path.exists(os.path.join(OUT, "ready.%d" % r))
                   for r in (0, 1)):
                break
            time.sleep(0.05)
        else:
            raise SystemExit("ready barrier timed out on rank %d" % rank)

        if rank == 0:
            from tools import fleetview
            rc = fleetview.main([
                "--base-port", str({base}), "--nranks", "2",
                "--format", "json", "--gate",
                "--out", os.path.join(OUT, "report.json")])
            with open(os.path.join(OUT, "gate_rc"), "w") as f:
                f.write(str(rc))
        # hold this rank's plane up until the verdict is on disk
        deadline = time.time() + 30
        while (time.time() < deadline
               and not os.path.exists(os.path.join(OUT, "gate_rc"))):
            time.sleep(0.1)
    """))
    rc = launch(str(script), [], nproc=2, telemetry_port=base,
                history_dir=str(hist_dir),
                backend_env=f"JAX_PLATFORMS=cpu,PYTHONPATH={REPO},"
                            "PDTPU_FLAGS_metrics=1,PDTPU_FLAGS_slo=0")
    assert rc == 0
    # --gate exited non-zero (3) while the job-level alert was firing
    assert (out / "gate_rc").read_text() == "3"
    report = json.load(open(out / "report.json"))
    al = report["alerts"]
    assert al["ranks_reporting"] == 2
    (job,) = al["alerts"]                    # deduped: ONE job-level alert
    assert job["slo"] == "fleet-bad" and job["severity"] == "page"
    assert job["state"] == "firing" and job["ranks"] == [0, 1]
    assert job["burn_short"] > 1.0 and job["metric"] == "t.fleet_slo"
    assert al["firing"] == [job]
    assert report["record"]["slo"] == {"alerts_firing": 1,
                                       "pages_firing": 1}
    # the burn-rate sparkline data survived the wire per rank
    burn = {k: v for k, v in report["burn_history"].items()
            if k.startswith("slo.burn_rate{slo=fleet-bad")}
    assert burn and all(set(v) == {"0", "1"} for v in burn.values())
    # and the launch --history_dir contract: every rank mirrored its ticks
    for r in (0, 1):
        lines = [json.loads(l) for l in
                 open(hist_dir / f"history.rank{r}.jsonl",
                      encoding="utf-8")]
        assert lines and lines[0]["rank"] == r
        assert any("t.fleet_slo" in ln["samples"] for ln in lines)
    # the job alert renders in the text view with its sparkline
    text = fleetview_render(report)
    assert "FIRING" in text and "fleet-bad:page" in text


def fleetview_render(report):
    from tools import fleetview
    return fleetview.render_text(report)


# ---------------------------------------------------------------------------
# observation-only: zero retraces / warm cache starts with the engine ON
# ---------------------------------------------------------------------------

@pytest.fixture
def _fresh_prog():
    import paddle_tpu.static as static
    from paddle_tpu.static import framework as _fw

    _fw._unique.counters = {}
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        yield main, startup


def _fc_tower():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L
    import numpy as np

    x = L.data("x", [32])
    y = L.data("y", [1])
    h = L.fc(x, 64, act="relu")
    pred = L.fc(h, 1)
    loss = L.mean(L.square_error_cost(pred, y))
    static.optimizer.SGD(learning_rate=0.01).minimize(loss)
    feed = {"x": np.zeros((16, 32), np.float32),
            "y": np.zeros((16, 1), np.float32)}
    return loss, feed


def _running_engine():
    """A started singleton engine with the shipped defaults plus a live
    objective over executor metrics, sampling aggressively."""
    flags.set_flags({"slo": True})
    eng = slo.engine()
    eng.register(slo.SLO("exec-step", "executor.step_time_ms", ">", 1e9,
                         objective_pct=99.0, signal="p99",
                         windows=[slo.Window(0.2, 1.0, 1.0, "page")]))
    eng.start(sample_secs=0.02)
    return eng


def test_zero_steady_state_retraces_with_engine_on(_fresh_prog,
                                                   _flags_guard):
    import paddle_tpu.static as static

    main, startup = _fresh_prog
    loss, feed = _fc_tower()
    eng = _running_engine()
    exe = static.Executor()
    exe.run(startup)
    exe.run(main, feed=feed, fetch_list=[loss])    # the one compile
    traces = monitor.counter("executor.traces")
    t0 = traces.value()
    for _ in range(8):
        exe.run(main, feed=feed, fetch_list=[loss])
    # keep stepping until the sampler has baselined + emitted the step-time
    # series (ticks every 20ms; runs are cached, so traces must not move)
    deadline = time.time() + 10.0
    while (not eng.history.match_series("executor.step_time_ms", ":p99")
           and time.time() < deadline):
        exe.run(main, feed=feed, fetch_list=[loss])
        time.sleep(0.02)
    assert traces.value() == t0                    # zero steady-state retraces
    assert eng.running
    # the sampler actually ran against this workload's metrics
    assert eng.history.match_series("executor.step_time_ms", ":p99")


def test_warm_compile_cache_start_with_engine_on(_fresh_prog, tmp_path,
                                                 _flags_guard):
    import paddle_tpu.static as static

    main, startup = _fresh_prog
    loss, feed = _fc_tower()
    flags.set_flags({"compile_cache_dir": str(tmp_path)})
    _running_engine()
    exe = static.Executor()
    exe.run(startup)
    exe.run(main, feed=feed, fetch_list=[loss])
    assert sorted(tmp_path.glob("*.pdtc")), "cold run stored no executables"
    traces = monitor.counter("executor.traces")
    t0 = traces.value()
    warm = static.Executor()                       # fresh hot map, same scope
    warm.run(main, feed=feed, fetch_list=[loss])
    assert traces.value() == t0                    # deserialized, not retraced


# ---------------------------------------------------------------------------
# tools: slocheck + metricsdump --lint --objectives
# ---------------------------------------------------------------------------

def test_slocheck_selfcheck_rides_tier1():
    r = subprocess.run(
        [sys.executable, "-m", "tools.slocheck", "--selfcheck"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "selfcheck OK" in r.stdout


def test_slocheck_validates_good_and_rejects_bad(tmp_path):
    good = tmp_path / "good.toml"
    good.write_text('[[slo]]\nname = "ttft"\nmetric = "serve.ttft_p99_ms"\n'
                    'op = ">"\nthreshold = 500.0\n')
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "tools.slocheck", str(good)],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 objectives OK" in r.stdout
    # unknown metric -> inventory failure, exit 1 with the objective named
    typo = tmp_path / "typo.toml"
    typo.write_text('[[slo]]\nname = "ttft"\nmetric = "serve.ttft_p99_msec"'
                    '\nop = ">"\nthreshold = 500.0\n')
    r = subprocess.run(
        [sys.executable, "-m", "tools.slocheck", str(typo)],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 1
    assert "serve.ttft_p99_msec" in r.stderr
    # structurally broken -> exit 1 with the parse diagnostic
    broken = tmp_path / "broken.toml"
    broken.write_text('[[slo]]\nname = "x"\nmetric = "t.m"\nop = "!="\n'
                      'threshold = 1.0\n')
    r = subprocess.run(
        [sys.executable, "-m", "tools.slocheck", str(broken)],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 1 and "invalid" in r.stderr


def test_slocheck_prom_inventory(tmp_path):
    """--prom validates against a dumped exposition instead of the static
    inventory (dots render as underscores on the wire)."""
    from tools import slocheck

    prom = tmp_path / "metrics.prom"
    prom.write_text("# TYPE serve_ttft_p99_ms gauge\n"
                    "serve_ttft_p99_ms 12.0\n"
                    "# TYPE t_req_ms histogram\n"
                    't_req_ms_bucket{le="+Inf"} 1\n'
                    "t_req_ms_sum 3.0\nt_req_ms_count 1\n")
    names = slocheck._prom_base_names(prom.read_text())
    assert names == {"serve_ttft_p99_ms", "t_req_ms"}
    obj = tmp_path / "obj.toml"
    obj.write_text('[[slo]]\nname = "a"\nmetric = "serve.ttft_p99_ms"\n'
                   'op = ">"\nthreshold = 1.0\n'
                   '[[slo]]\nname = "b"\nmetric = "t.req_ms"\nop = ">"\n'
                   'threshold = 1.0\n')
    assert slocheck.check_file(str(obj), prom_names=names) == []
    missing = tmp_path / "missing.toml"
    missing.write_text('[[slo]]\nname = "a"\nmetric = "serve.nope"\n'
                       'op = ">"\nthreshold = 1.0\n')
    problems = slocheck.check_file(str(missing), prom_names=names)
    assert problems and problems[0][0] == "serve.nope"


def test_metricsdump_lint_objectives(tmp_path):
    from tools import metricsdump

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"slo": [
        {"name": "x", "metric": "serve.ttft_p99_ms", "op": ">",
         "threshold": 500.0}]}))
    assert metricsdump.lint_objectives(str(good)) == []
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"slo": [
        {"name": "x", "metric": "serve.no_such", "op": ">",
         "threshold": 1.0}]}))
    problems = metricsdump.lint_objectives(str(bad))
    assert problems and problems[0][0] == "serve.no_such"
    # a file that fails to load is one problem, not a crash
    assert metricsdump.lint_objectives(str(tmp_path / "nope.toml"))
    # and the CLI path wires it into --lint's exit code
    r = subprocess.run(
        [sys.executable, "-m", "tools.metricsdump", "--lint",
         "--objectives", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 1
    assert "serve.no_such" in r.stderr
