"""Distributed tracing + flight recorder (utils/trace.py, tools/tracecat.py):
span context propagation across PS RPCs and launch ranks, post-mortem dumps,
and the trace-merging CLI.  Ref: the reference's tools/timeline.py merges
per-process CUPTI timelines offline; here correlation is by shared trace_id."""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.utils import monitor, profiler, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# span context / propagation primitives
# ---------------------------------------------------------------------------

def test_traceparent_roundtrip_and_malformed():
    ctx = trace.SpanContext()
    tp = ctx.to_traceparent()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = trace.SpanContext.from_traceparent(tp)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    for bad in ("", "junk", "00-zz-11-01", "01-" + "a" * 32 + "-" + "b" * 16):
        assert trace.SpanContext.from_traceparent(bad) is None
    assert trace.extract(None) is None
    assert trace.extract({}) is None
    assert trace.extract({"traceparent": tp}).span_id == ctx.span_id


def test_span_nesting_and_inject():
    assert trace.current_span() is None
    with trace.span("outer") as a:
        assert trace.current_span() is a
        with trace.span("inner") as b:
            assert b.context.trace_id == a.context.trace_id
            assert b.context.parent_id == a.context.span_id
            carrier = trace.inject({})
            assert carrier["traceparent"] == b.context.to_traceparent()
        assert trace.current_span() is a
    assert trace.current_span() is None
    # no current span: inject leaves the carrier untouched
    assert trace.inject({}) == {}


def test_explicit_parent_wins_over_current():
    remote = trace.SpanContext()
    with trace.span("local"):
        with trace.span("handler", parent=remote) as h:
            assert h.context.trace_id == remote.trace_id
            assert h.context.parent_id == remote.span_id


def test_span_lands_in_native_event_store():
    profiler.start_profiler()
    with trace.span("trace_test::probe"):
        time.sleep(0.001)
    assert "trace_test::probe" in profiler.summary()


def test_span_as_decorator():
    @trace.span("trace_test::deco")
    def f(x):
        assert trace.current_span() is not None
        return x + 1

    assert f(1) == 2
    assert trace.current_span() is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_is_bounded(tmp_path):
    fr = trace.FlightRecorder(size=5)
    for i in range(20):
        fr.record("tick", name=f"n{i}", i=i)
    evs = fr.events()
    assert len(evs) == 5
    assert [e["i"] for e in evs] == [15, 16, 17, 18, 19]
    # dump is valid JSON with meta + events
    path = str(tmp_path / "flight.json")
    assert fr.dump(path) == 5
    doc = json.load(open(path))
    assert doc["meta"]["size"] == 5 and len(doc["events"]) == 5


def test_flight_recorder_stamps_span_context():
    fr = trace.FlightRecorder(size=8)
    with trace.span("ctx_holder") as sp:
        fr.record("probe", name="p")
    ev = fr.events()[-1]
    assert ev["trace_id"] == sp.context.trace_id
    assert ev["span_id"] == sp.context.span_id
    # non-JSON fields are made safe
    fr.record("odd", name="o", arr=np.arange(2))
    json.dumps(fr.events()[-1])


def test_flight_recorder_size_flag():
    from paddle_tpu.core import flags
    old = flags.get_flag("flight_recorder_size")
    try:
        flags.set_flags({"flight_recorder_size": 3})
        assert trace.FlightRecorder().size == 3
    finally:
        flags.set_flags({"flight_recorder_size": old})


# ---------------------------------------------------------------------------
# post-mortem dumps (subprocess: excepthook and SIGTERM paths)
# ---------------------------------------------------------------------------

def _run_worker(tmp_path, body, env_extra, check=False):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(body))
    env = dict(os.environ)
    env.pop("PDTPU_TRACE_DIR", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra)
    return subprocess.run([sys.executable, str(script)], env=env, cwd=REPO,
                          capture_output=True, text=True, check=check,
                          timeout=120)


def test_dump_on_uncaught_exception(tmp_path):
    tdir = tmp_path / "tr"
    proc = _run_worker(tmp_path, """
        import paddle_tpu
        from paddle_tpu.utils import trace
        with trace.span("doomed::step", step=3):
            raise RuntimeError("boom at step 3")
    """, {"PDTPU_TRACE_DIR": str(tdir), "PADDLE_TRAINER_ID": "0",
          "JAX_PLATFORMS": "cpu"})
    assert proc.returncode != 0 and "boom at step 3" in proc.stderr
    doc = json.load(open(tdir / "flight.rank0.json"))
    kinds = {e["kind"] for e in doc["events"]}
    assert "exception" in kinds and "worker_start" in kinds
    exc = [e for e in doc["events"] if e["kind"] == "exception"][-1]
    assert exc["name"] == "RuntimeError" and "boom" in exc["message"]
    # the atexit chrome trace is also present and valid
    chrome = json.load(open(tdir / "trace.rank0.json"))
    names = {e.get("name") for e in chrome["traceEvents"]}
    assert "doomed::step" in names


def test_dump_on_sigterm(tmp_path):
    tdir = tmp_path / "tr"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import sys, time
        import paddle_tpu
        from paddle_tpu.utils import trace
        trace.flight_recorder().record("phase", name="spinning")
        print("ready", flush=True)
        time.sleep(60)
    """))
    env = dict(os.environ)
    env.update({"PDTPU_TRACE_DIR": str(tdir), "PADDLE_TRAINER_ID": "0",
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                    "PYTHONPATH", "")})
    proc = subprocess.Popen([sys.executable, str(script)], env=env, cwd=REPO,
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 128 + signal.SIGTERM
    doc = json.load(open(tdir / "flight.rank0.json"))
    kinds = [e["kind"] for e in doc["events"]]
    assert "signal" in kinds and "phase" in kinds
    sig = [e for e in doc["events"] if e["kind"] == "signal"][-1]
    assert sig["name"] == "SIGTERM"


# ---------------------------------------------------------------------------
# cross-process propagation: PS RPC client span -> server handler span
# ---------------------------------------------------------------------------

def test_ps_rpc_propagates_trace_context(tmp_path):
    from paddle_tpu.distributed.ps_server import RemoteSparseTable

    tdir = tmp_path / "tr"
    script = tmp_path / "server.py"
    script.write_text(textwrap.dedent("""
        import sys, time
        import paddle_tpu
        from paddle_tpu.distributed.ps import SparseTable
        from paddle_tpu.distributed.ps_server import PSServer
        server = PSServer(SparseTable(4, 2, optimizer="sgd"), port=0)
        server.start()
        print(server.endpoint, flush=True)
        while server._running:
            time.sleep(0.05)
        time.sleep(0.3)  # let the handler thread finish its span records
    """))
    env = dict(os.environ)
    env.update({"PDTPU_TRACE_DIR": str(tdir), "PADDLE_TRAINER_ID": "1",
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                    "PYTHONPATH", "")})
    proc = subprocess.Popen([sys.executable, str(script)], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        endpoint = proc.stdout.readline().strip()
        assert ":" in endpoint, proc.stderr.read()
        table = RemoteSparseTable([endpoint], dim=4)
        with trace.span("trainer::lookup") as sp:
            rows = table.pull(np.asarray([1, 2, 3], np.int64))
            client_trace = sp.context.trace_id
            client_span = sp.context.span_id
        assert rows.shape == (3, 4)
        table.shutdown_servers()
        table.close()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    doc = json.load(open(tdir / "flight.rank1.json"))
    pulls = [e for e in doc["events"]
             if e["kind"] == "span_begin" and e["name"] == "ps::pull"]
    assert pulls, [e["name"] for e in doc["events"]]
    # server handler span carries the CLIENT's trace_id (one distributed
    # trace across the process gap), parented under the client's rpc span
    assert pulls[-1]["trace_id"] == client_trace
    assert pulls[-1]["parent_id"] != client_span  # parent is the rpc span,
    assert "parent_id" in pulls[-1]               # not the outer one


# ---------------------------------------------------------------------------
# launch-level: shared job trace_id + per-rank traces merge via tracecat
# ---------------------------------------------------------------------------

def test_launch_shares_job_trace_id_and_tracecat_merges(tmp_path):
    from paddle_tpu.distributed.launch import launch
    from tools.tracecat import merge_traces

    out_dir = tmp_path / "out"
    out_dir.mkdir()
    tdir = tmp_path / "traces"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import json, os, time
        import paddle_tpu
        from paddle_tpu.utils import trace
        rank = os.environ["PADDLE_TRAINER_ID"]
        with trace.span("worker::step", rank=int(rank)):
            time.sleep(0.01)
        info = {{"trace_id": os.environ["PDTPU_TRACE_ID"],
                 "job_id": trace.job_trace_id()}}
        with open(os.path.join({str(out_dir)!r}, f"r{{rank}}.json"), "w") as f:
            json.dump(info, f)
    """))
    rc = launch(str(script), [], nproc=2, trace_dir=str(tdir),
                backend_env=f"JAX_PLATFORMS=cpu,PYTHONPATH={REPO}")
    assert rc == 0
    infos = [json.load(open(out_dir / f"r{r}.json")) for r in range(2)]
    # one job-level trace_id, shared by both ranks and adopted in-process
    assert infos[0]["trace_id"] == infos[1]["trace_id"]
    assert all(i["job_id"] == i["trace_id"] for i in infos)

    rank_traces = [str(tdir / f"trace.rank{r}.json") for r in range(2)]
    assert all(os.path.exists(p) for p in rank_traces)
    merged = merge_traces(rank_traces)
    events = merged["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    assert any(e["name"] == "worker::step" for e in xs)
    metas = [e for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert {m["pid"] for m in metas} == {0, 1}
    # both ranks' flight dumps carry the SAME job trace_id in their meta
    flights = [json.load(open(tdir / f"flight.rank{r}.json"))
               for r in range(2)]
    assert flights[0]["meta"]["trace_id"] == flights[1]["meta"]["trace_id"]
    assert flights[0]["meta"]["trace_id"] == infos[0]["trace_id"]


def test_tracecat_selfcheck_cli():
    proc = subprocess.run([sys.executable, "-m", "tools.tracecat",
                           "--selfcheck"], cwd=REPO, capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_tracecat_merge_and_flight_cli(tmp_path):
    t = {"traceEvents": [
        {"name": "s", "ph": "X", "pid": 999, "tid": 1, "ts": 0, "dur": 10}]}
    p0 = tmp_path / "trace.rank0.json"
    p0.write_text(json.dumps(t))
    out = tmp_path / "merged.json"
    proc = subprocess.run([sys.executable, "-m", "tools.tracecat", "merge",
                           str(p0), "--out", str(out)], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(out))
    assert all(e["pid"] == 0 for e in doc["traceEvents"])

    fl = tmp_path / "flight.rank0.json"
    fl.write_text(json.dumps({"meta": {"rank": 0}, "events": [
        {"ts": 1.0, "kind": "nan", "name": "grads",
         "trace_id": "ab" * 16, "span_id": "cd" * 8}]}))
    proc = subprocess.run([sys.executable, "-m", "tools.tracecat", "flight",
                           str(fl)], cwd=REPO, capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0 and "nan" in proc.stdout
    assert "abababab" in proc.stdout


# ---------------------------------------------------------------------------
# satellites: nan counter + flight event, stop_profiler stream, rank-aware
# chrome export
# ---------------------------------------------------------------------------

def test_check_numerics_counts_and_flight_records():
    from paddle_tpu.utils import debug

    reg = monitor.default_registry()
    c = reg.get("debug.nan_events")
    before = c.value(tag="trace_test_grads")
    trace.flight_recorder().clear()
    with pytest.raises(FloatingPointError, match="trace_test_grads"):
        debug.check_numerics({"w": np.asarray([1.0, np.nan])},
                             tag="trace_test_grads", force=True)
    assert c.value(tag="trace_test_grads") == before + 1
    nans = [e for e in trace.flight_recorder().events()
            if e["kind"] == "nan" and e["name"] == "trace_test_grads"]
    assert nans and any("w" in leaf for leaf in nans[-1]["leaves"])


def test_stop_profiler_accepts_stream_and_logger():
    import io

    profiler.start_profiler()
    with profiler.RecordEvent("trace_test::summary"):
        pass
    buf = io.StringIO()
    profiler.stop_profiler(sorted_key="total", stream=buf)
    assert "trace_test::summary" in buf.getvalue()

    class FakeLogger:
        def __init__(self):
            self.lines = []

        def info(self, msg):
            self.lines.append(msg)

    profiler.start_profiler()
    with profiler.RecordEvent("trace_test::summary2"):
        pass
    lg = FakeLogger()
    profiler.stop_profiler(stream=lg)
    assert any("trace_test::summary2" in ln for ln in lg.lines)
    with pytest.raises(TypeError):
        profiler.stop_profiler(stream=object())


def test_export_chrome_tracing_rank_aware(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    profiler.start_profiler()
    with profiler.RecordEvent("trace_test::ranked"):
        pass
    path = str(tmp_path / "chrome.json")
    profiler.export_chrome_tracing(path)
    doc = json.load(open(path))
    events = doc["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs and all(e["pid"] == 3 for e in xs)
    metas = {e["name"]: e for e in events if e.get("ph") == "M"}
    assert metas["process_name"]["args"]["name"] == "paddle_tpu rank 3"
    assert metas["process_sort_index"]["args"]["sort_index"] == 3
    assert metas["process_name"]["pid"] == 3
