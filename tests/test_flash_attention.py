"""Pallas flash-attention kernel: interpret-mode numerics vs the jnp
reference (ops/attention.py), including padding bias, causal, dropout replay,
and the backward kernels.

The reference framework has no flash attention (SURVEY.md §5.7); the oracle
here is the O(S^2) reference implementation the kernel must agree with.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.ops.attention import scaled_dot_product_attention as sdpa
from paddle_tpu.ops.pallas import flash_attention as fa

B, H, S, D = 2, 3, 128, 64


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture
def pad_bias():
    bias = np.zeros((B, S), np.float32)
    bias[0, 100:] = -1e4  # batch 0: 100 valid tokens
    return jnp.asarray(bias)


def _mask4d(bias):
    return bias[:, None, None, :]


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(qkv, pad_bias, causal):
    q, k, v = qkv
    out = fa.flash_attention(q, k, v, bias=pad_bias, causal=causal,
                             block_q=64, block_k=64)
    ref = sdpa(q, k, v, attn_mask=_mask4d(pad_bias), is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_no_bias_uneven_blocks(qkv):
    q, k, v = qkv
    out = fa.flash_attention(q, k, v, block_q=128, block_k=32)
    ref = sdpa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_reference(qkv, pad_bias, causal):
    q, k, v = qkv

    def loss_k(q, k, v):
        return (fa.flash_attention(q, k, v, bias=pad_bias, causal=causal,
                                   block_q=64, block_k=64) ** 2).sum()

    def loss_r(q, k, v):
        return (sdpa(q, k, v, attn_mask=_mask4d(pad_bias),
                     is_causal=causal) ** 2).sum()

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        scale = float(jnp.abs(b).max())
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=scale * 1e-5)


def test_dropout_deterministic_and_block_independent(qkv, pad_bias):
    q, k, v = qkv
    seed = jnp.array([1234], jnp.int32)
    args = dict(bias=pad_bias, dropout_rate=0.3, seed=seed)
    o1 = fa.flash_attention(q, k, v, block_q=64, block_k=64, **args)
    o2 = fa.flash_attention(q, k, v, block_q=64, block_k=64, **args)
    assert bool((o1 == o2).all())
    o3 = fa.flash_attention(q, k, v, block_q=32, block_k=128, **args)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o3),
                               rtol=1e-5, atol=1e-5)
    o4 = fa.flash_attention(q, k, v, block_q=64, block_k=64, bias=pad_bias,
                            dropout_rate=0.3, seed=jnp.array([9], jnp.int32))
    assert bool((o1 != o4).any())


def test_dropout_grads_match_same_mask_reference(qkv, pad_bias):
    """Backward with dropout replays the identical keep mask: compare against
    a jnp attention using the hash-derived mask computed outside the kernel."""
    q, k, v = qkv
    seed = jnp.array([77], jnp.int32)
    rate = 0.3
    qpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    keeps = jnp.stack([
        fa._dropout_keep(seed[0], jnp.int32(i), qpos, kpos, rate)
        for i in range(B * H)]).reshape(B, H, S, S)

    def ref(q, k, v):
        sm = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        sm = sm + pad_bias[:, None, None, :]
        p = jax.nn.softmax(sm, -1)
        p = jnp.where(keeps, p / (1 - rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def loss_k(*a):
        return (fa.flash_attention(*a, bias=pad_bias, dropout_rate=rate,
                                   seed=seed, block_q=64, block_k=64) ** 2).sum()

    out_k = fa.flash_attention(q, k, v, bias=pad_bias, dropout_rate=rate,
                               seed=seed, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref(q, k, v)),
                               rtol=1e-5, atol=1e-5)
    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        scale = float(jnp.abs(b).max())
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=scale * 1e-5)


def test_dropout_keep_rate():
    qpos = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
    keep = fa._dropout_keep(jnp.int32(42), jnp.int32(0), qpos, kpos, 0.3)
    rate = 1.0 - float(keep.mean())
    assert abs(rate - 0.3) < 0.01


class TestDispatch:
    def test_padding_bias_extraction(self):
        b, s = 2, 128
        add = jnp.zeros((b, 1, 1, s), jnp.float32)
        assert attn_ops._as_padding_bias(add, b, s).shape == (b, s)
        boolm = jnp.ones((1, 1, 1, s), bool)
        out = attn_ops._as_padding_bias(boolm, b, s)
        assert out.shape == (b, s) and float(out.max()) == 0.0
        # full (b, h, sq, sk) masks are not kernel-eligible
        assert attn_ops._as_padding_bias(
            jnp.zeros((b, 1, s, s)), b, s) is None
        assert attn_ops._as_padding_bias(
            jnp.zeros((b, 4, 1, s)), b, s) is None

    def test_none_mask_gives_zero_bias(self):
        out = attn_ops._as_padding_bias(None, 3, 64)
        assert out.shape == (3, 64) and float(jnp.abs(out).max()) == 0.0

    def test_flash_fallback_matches_sdpa_with_general_mask(self):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 64, 32)), jnp.float32)
        mask = jnp.asarray(rng.normal(size=(1, 2, 64, 64)), jnp.float32)
        out = attn_ops.flash_attention(q, q, q, attn_mask=mask)
        ref = sdpa(q, q, q, attn_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
