"""Pallas flash-attention kernel: interpret-mode numerics vs the jnp
reference (ops/attention.py), including padding bias, causal, dropout replay,
and the backward kernels.

The reference framework has no flash attention (SURVEY.md §5.7); the oracle
here is the O(S^2) reference implementation the kernel must agree with.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.ops.attention import scaled_dot_product_attention as sdpa
from paddle_tpu.ops.pallas import flash_attention as fa

B, H, S, D = 2, 3, 128, 64


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture
def pad_bias():
    bias = np.zeros((B, S), np.float32)
    bias[0, 100:] = -1e4  # batch 0: 100 valid tokens
    return jnp.asarray(bias)


def _mask4d(bias):
    return bias[:, None, None, :]


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(qkv, pad_bias, causal):
    q, k, v = qkv
    out = fa.flash_attention(q, k, v, bias=pad_bias, causal=causal,
                             block_q=64, block_k=64)
    ref = sdpa(q, k, v, attn_mask=_mask4d(pad_bias), is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_no_bias_uneven_blocks(qkv):
    q, k, v = qkv
    out = fa.flash_attention(q, k, v, block_q=128, block_k=32)
    ref = sdpa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_reference(qkv, pad_bias, causal):
    q, k, v = qkv

    def loss_k(q, k, v):
        return (fa.flash_attention(q, k, v, bias=pad_bias, causal=causal,
                                   block_q=64, block_k=64) ** 2).sum()

    def loss_r(q, k, v):
        return (sdpa(q, k, v, attn_mask=_mask4d(pad_bias),
                     is_causal=causal) ** 2).sum()

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        scale = float(jnp.abs(b).max())
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=scale * 1e-5)


def test_dropout_deterministic_and_block_independent(qkv, pad_bias):
    q, k, v = qkv
    seed = jnp.array([1234], jnp.int32)
    args = dict(bias=pad_bias, dropout_rate=0.3, seed=seed)
    o1 = fa.flash_attention(q, k, v, block_q=64, block_k=64, **args)
    o2 = fa.flash_attention(q, k, v, block_q=64, block_k=64, **args)
    assert bool((o1 == o2).all())
    o3 = fa.flash_attention(q, k, v, block_q=32, block_k=128, **args)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o3),
                               rtol=1e-5, atol=1e-5)
    o4 = fa.flash_attention(q, k, v, block_q=64, block_k=64, bias=pad_bias,
                            dropout_rate=0.3, seed=jnp.array([9], jnp.int32))
    assert bool((o1 != o4).any())


def test_dropout_grads_match_same_mask_reference(qkv, pad_bias):
    """Backward with dropout replays the identical keep mask: compare against
    a jnp attention using the hash-derived mask computed outside the kernel."""
    q, k, v = qkv
    seed = jnp.array([77], jnp.int32)
    rate = 0.3
    qpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    keeps = jnp.stack([
        fa._dropout_keep(seed[0], jnp.int32(i), qpos, kpos, rate)
        for i in range(B * H)]).reshape(B, H, S, S)

    def ref(q, k, v):
        sm = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        sm = sm + pad_bias[:, None, None, :]
        p = jax.nn.softmax(sm, -1)
        p = jnp.where(keeps, p / (1 - rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def loss_k(*a):
        return (fa.flash_attention(*a, bias=pad_bias, dropout_rate=rate,
                                   seed=seed, block_q=64, block_k=64) ** 2).sum()

    out_k = fa.flash_attention(q, k, v, bias=pad_bias, dropout_rate=rate,
                               seed=seed, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref(q, k, v)),
                               rtol=1e-5, atol=1e-5)
    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        scale = float(jnp.abs(b).max())
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=scale * 1e-5)


def test_dropout_keep_rate():
    qpos = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
    keep = fa._dropout_keep(jnp.int32(42), jnp.int32(0), qpos, kpos, 0.3)
    rate = 1.0 - float(keep.mean())
    assert abs(rate - 0.3) < 0.01


class TestDispatch:
    def test_padding_bias_extraction(self):
        b, s = 2, 128
        add = jnp.zeros((b, 1, 1, s), jnp.float32)
        assert attn_ops._as_padding_bias(add, b, s).shape == (b, s)
        boolm = jnp.ones((1, 1, 1, s), bool)
        out = attn_ops._as_padding_bias(boolm, b, s)
        assert out.shape == (b, s) and float(out.max()) == 0.0
        # full (b, h, sq, sk) masks are not kernel-eligible
        assert attn_ops._as_padding_bias(
            jnp.zeros((b, 1, s, s)), b, s) is None
        assert attn_ops._as_padding_bias(
            jnp.zeros((b, 4, 1, s)), b, s) is None

    def test_none_mask_gives_zero_bias(self):
        out = attn_ops._as_padding_bias(None, 3, 64)
        assert out.shape == (3, 64) and float(jnp.abs(out).max()) == 0.0

    def test_flash_fallback_matches_sdpa_with_general_mask(self):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 64, 32)), jnp.float32)
        mask = jnp.asarray(rng.normal(size=(1, 2, 64, 64)), jnp.float32)
        out = attn_ops.flash_attention(q, q, q, attn_mask=mask)
        ref = sdpa(q, q, q, attn_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)


# -- packed layout (no head transposes) --------------------------------------

class TestPackedLayout:
    def _data(self, b=2, h=4, s=256, d=64, dtype=jnp.float32):
        rng = np.random.default_rng(0)
        q4, k4, v4 = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)), dtype)
                      for _ in range(3))
        bias = jnp.asarray(rng.normal(0, 1, (b, s)), jnp.float32)
        pack = lambda t: jnp.moveaxis(t, 1, 2).reshape(b, s, h * d)
        return q4, k4, v4, bias, pack

    def test_packed_matches_standard_kernel_fwd_and_grads(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention as std
        from paddle_tpu.ops.pallas.flash_attention_packed import (
            flash_attention_packed as packed,
        )

        q4, k4, v4, bias, pack = self._data()
        b, h, s, d = q4.shape
        ref = std(q4, k4, v4, bias=bias)
        out = packed(pack(q4), pack(k4), pack(v4), h, bias=bias)
        out4 = jnp.moveaxis(out.reshape(b, s, h, d), 2, 1)
        np.testing.assert_allclose(np.asarray(out4), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        g_ref = jax.grad(lambda t: (std(t[0], t[1], t[2], bias=bias) ** 2
                                    ).sum())((q4, k4, v4))
        g_pk = jax.grad(lambda t: (packed(pack(t[0]), pack(t[1]), pack(t[2]),
                                          h, bias=bias) ** 2).sum())(
            (q4, k4, v4))
        for name, a, r in zip("qkv", g_pk, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-5, atol=1e-5, err_msg=name)

    def test_packed_multi_block_and_head_dim_128(self):
        """seq > block (lse/delta slicing regression) and 128-wide heads."""
        from paddle_tpu.ops.pallas.flash_attention import flash_attention as std
        from paddle_tpu.ops.pallas.flash_attention_packed import (
            flash_attention_packed as packed,
        )

        for h, d, s in ((2, 64, 1024), (3, 128, 512)):
            b = 1
            rng = np.random.default_rng(0)
            q4, k4, v4 = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)),
                                      jnp.float32) for _ in range(3))
            pack = lambda t: jnp.moveaxis(t, 1, 2).reshape(b, s, h * d)
            ref = std(q4, k4, v4, block_q=256, block_k=256)
            g_ref = jax.grad(lambda t: (std(t[0], t[1], t[2], block_q=256,
                                            block_k=256) ** 2).sum())(
                (q4, k4, v4))
            out = packed(pack(q4), pack(k4), pack(v4), h, block_q=256,
                         block_k=256)
            g_pk = jax.grad(lambda t: (packed(pack(t[0]), pack(t[1]),
                                              pack(t[2]), h, block_q=256,
                                              block_k=256) ** 2).sum())(
                (q4, k4, v4))
            out4 = jnp.moveaxis(out.reshape(b, s, h, d), 2, 1)
            np.testing.assert_allclose(np.asarray(out4), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
            for a, r in zip(g_pk, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                           rtol=1e-5, atol=1e-5)

    def test_packed_causal_and_dropout_replay(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention as std
        from paddle_tpu.ops.pallas.flash_attention_packed import (
            flash_attention_packed as packed,
        )

        q4, k4, v4, bias, pack = self._data(s=128)
        b, h, s, d = q4.shape
        ref = std(q4, k4, v4, bias=bias, causal=True)
        out = packed(pack(q4), pack(k4), pack(v4), h, bias=bias, causal=True)
        out4 = jnp.moveaxis(out.reshape(b, s, h, d), 2, 1)
        np.testing.assert_allclose(np.asarray(out4), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # causal MULTI-BLOCK bounds (num_kv_iter clamp / qi_start) incl grads
        q4, k4, v4, bias, pack = self._data(s=1024)
        b, h, s, d = q4.shape
        ref = std(q4, k4, v4, bias=bias, causal=True, block_q=256,
                  block_k=256)
        g_ref = jax.grad(lambda t: (std(t[0], t[1], t[2], bias=bias,
                                        causal=True, block_q=256,
                                        block_k=256) ** 2).sum())((q4, k4, v4))
        out = packed(pack(q4), pack(k4), pack(v4), h, bias=bias, causal=True,
                     block_q=256, block_k=256)
        g_pk = jax.grad(lambda t: (packed(pack(t[0]), pack(t[1]), pack(t[2]),
                                          h, bias=bias, causal=True,
                                          block_q=256, block_k=256) ** 2
                                   ).sum())((q4, k4, v4))
        out4 = jnp.moveaxis(out.reshape(b, s, h, d), 2, 1)
        np.testing.assert_allclose(np.asarray(out4), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        for a, r in zip(g_pk, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-5, atol=1e-5)
        with pytest.raises(ValueError):
            packed(pack(q4)[..., :q4.shape[1] * 96 // 64], pack(k4), pack(v4),
                   h)  # head_dim 96: unsupported layout must raise
        seed = jnp.asarray([5], jnp.int32)
        a1 = packed(pack(q4), pack(k4), pack(v4), h, dropout_rate=0.2,
                    seed=seed)
        a2 = packed(pack(q4), pack(k4), pack(v4), h, dropout_rate=0.2,
                    seed=seed)
        assert np.array_equal(np.asarray(a1), np.asarray(a2))

    def test_mha_packed_dispatch(self, monkeypatch):
        """MultiHeadAttention takes the transpose-free path when the gate
        opens and matches the split-head fallback."""
        import paddle_tpu.nn as nn
        from paddle_tpu.autograd import functional_call, parameters_dict
        from paddle_tpu.ops import attention as attn_mod

        mha = nn.MultiHeadAttention(128, 2)
        mha.eval()
        p = parameters_dict(mha)
        x = jnp.asarray(np.random.default_rng(1).normal(0, 1, (2, 128, 128)),
                        jnp.float32)
        ref = functional_call(mha, p, (x,))
        calls = []
        orig = attn_mod.flash_attention_packed

        def spy(*a, **k):
            out = orig(*a, **k)
            calls.append(out is not None)
            return out

        monkeypatch.setattr(attn_mod, "flash_attention_packed", spy)
        monkeypatch.setattr(attn_mod, "_is_tpu", lambda: True)
        out = functional_call(mha, p, (x,))
        assert calls == [True], "packed path did not engage"
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
