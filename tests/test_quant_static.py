"""Static-graph quantization: QAT transform/freeze passes + PTQ.

Reference contract: slim/quantization/quantization_pass.py
(QuantizationTransformPass/QuantizationFreezePass over the IrGraph) and
post_training_quantization.py (calibrate a saved model, emit fixed-scale
int8); the judge's bar — a quantized LeNet book model trains/infers with
int8-simulated weights and round-trips through static.save/load.
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.slim import (
    QuantizationFreezePass,
    QuantizationTransformPass,
    quant_static,
)
from paddle_tpu.static import layers as L

RNG = np.random.RandomState(11)


def _lenet_program():
    """The recognize_digits book LeNet (ref book/chapter 2) on 14x14."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("img", (1, 14, 14))
        label = static.data("label", (1,), dtype="int64")
        c1 = L.conv2d(img, 4, 5, padding=2, act="relu")
        p1 = L.pool2d(c1, 2, "max", 2)
        c2 = L.conv2d(p1, 8, 5, padding=2, act="relu")
        p2 = L.pool2d(c2, 2, "max", 2)
        logits = L.fc(L.flatten(p2), 10)
        loss = L.mean(L.cross_entropy(L.softmax(logits), label))
    return main, startup, img, label, logits, loss


def _feed(n=8):
    return {"img": RNG.rand(n, 1, 14, 14).astype(np.float32),
            "label": RNG.randint(0, 10, (n, 1)).astype(np.int64)}


def _count(program, op_type):
    return sum(1 for op in program.global_block().ops
               if op.type == op_type)


def test_qat_transform_freeze_save_load_roundtrip(tmp_path):
    main, startup, img, label, logits, loss = _lenet_program()
    with static.program_guard(main, startup):
        static.optimizer.SGD(0.05).minimize(loss)

    pass_ = QuantizationTransformPass()
    pass_.apply(main, startup)
    # 3 weights (2 convs + fc) quantized channel-wise, 3 activations
    assert _count(main, "fake_channel_wise_quantize_dequantize_abs_max") == 3
    assert _count(
        main, "fake_quantize_dequantize_moving_average_abs_max") == 3

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        losses = []
        feed = _feed()
        for _ in range(12):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0], losses  # QAT trains
        # the moving-average activation scale state advanced
        state_names = [n for n in main.global_block().vars
                       if n.endswith("@quant_moving_scale")]
        assert len(state_names) == 3
        assert all(float(np.asarray(scope.find_var(n)).reshape(-1)[0]) > 0
                   for n in state_names)

        # freeze: weights become int8-simulated, act quant gets fixed scale
        infer = main.clone(for_test=True)
        QuantizationFreezePass(scope).apply(infer)
        assert _count(infer,
                      "fake_quantize_dequantize_moving_average_abs_max") == 0
        assert _count(infer, "fake_quantize_dequantize_fixed_scale") == 3
        # a frozen weight takes at most 255 distinct values per channel
        wname = next(n for n in infer.global_block().vars
                     if isinstance(infer.global_block().vars[n],
                                   static.framework.Parameter))
        w = np.asarray(scope.find_var(wname))
        scale = np.abs(w).max(axis=tuple(range(1, w.ndim)))
        q = w / (scale.reshape((-1,) + (1,) * (w.ndim - 1)) / 127)
        np.testing.assert_allclose(q, np.round(q), atol=1e-3)

        before, = exe.run(infer, feed=_feed(4), fetch_list=[logits])

        # round-trip through static.save/load
        prefix = str(tmp_path / "lenet_q")
        static.save(infer, prefix, exe, scope=scope)

    scope2 = static.Scope()
    with static.scope_guard(scope2):
        prog2, feeds, _ = static.load(prefix, exe, scope=scope2)
        after, = exe.run(prog2, feed=_feed(4), fetch_list=[logits.name])
    # same weights, same program -> different data, but deterministic run:
    # re-run the ORIGINAL feed through both to compare
    with static.scope_guard(scope):
        a, = exe.run(infer, feed=_feed(4), fetch_list=[logits])
    assert before.shape == (4, 10) and after.shape == (4, 10)
    assert np.isfinite(after).all()


def test_qat_freeze_preserves_accuracy_shape():
    """Frozen int8-simulated inference stays close to the QAT forward."""
    main, startup, img, label, logits, loss = _lenet_program()
    pass_ = QuantizationTransformPass()
    pass_.apply(main, startup)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        feed = _feed(4)
        qat_out, = exe.run(main, feed=feed, fetch_list=[logits])
        infer = main.clone(for_test=True)
        QuantizationFreezePass(scope).apply(infer)
        frozen_out, = exe.run(infer, feed=feed, fetch_list=[logits])
    np.testing.assert_allclose(qat_out, frozen_out, atol=0.2, rtol=0.2)


def test_post_training_quantization_over_saved_program(tmp_path):
    main, startup, img, label, logits, loss = _lenet_program()
    exe = static.Executor()
    scope = static.Scope()
    prefix = str(tmp_path / "lenet_fp32")
    with static.scope_guard(scope):
        exe.run(startup)
        float_out, = exe.run(main, feed=_feed(4), fetch_list=[logits])
        static.save(main, prefix, exe, scope=scope)

    scope2 = static.Scope()
    with static.scope_guard(scope2):
        def calib():
            for _ in range(3):
                yield _feed(4)

        ptq = quant_static.PostTrainingQuantization(
            exe, model_prefix=prefix, batch_generator=calib, batch_nums=3,
            scope=scope2)
        qprog = ptq.quantize()
        # activations got fixed-scale quant nodes, weights got scales
        assert _count(qprog, "fake_quantize_dequantize_fixed_scale") >= 2
        wops = [op for op in qprog.global_block().ops
                if op.type in ("conv2d", "mul")]
        assert any("weight_scale" in op.attrs for op in wops)
        q_out, = exe.run(qprog, feed=_feed(4), fetch_list=[logits.name])
        assert np.isfinite(q_out).all()
        out_prefix = str(tmp_path / "lenet_int8")
        ptq.save_quantized_model(out_prefix)

    # the quantized package reloads and infers
    scope3 = static.Scope()
    with static.scope_guard(scope3):
        prog3, _, _ = static.load(out_prefix, exe, scope=scope3)
        out3, = exe.run(prog3, feed=_feed(4), fetch_list=[logits.name])
    assert np.isfinite(out3).all()
