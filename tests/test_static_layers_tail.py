"""DSL long tail: every wrapper added for already-registered lowerings runs
through the Executor and matches a numpy/jax oracle (the reference's OpTest
check_output pattern, unittests/op_test.py:948)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.static as static
from paddle_tpu.static import layers as L


@pytest.fixture(autouse=True)
def _fresh():
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        yield main


def _run(main, feed, fetch):
    exe = static.Executor()
    return exe.run(main, feed=feed, fetch_list=fetch)


X = np.linspace(-2, 2, 12).reshape(3, 4).astype(np.float32)


UNARY_CASES = [
    ("exp", np.exp), ("log", lambda x: np.log(np.abs(x) + 2.5)),
    ("sqrt", lambda x: np.sqrt(np.abs(x) + 2.5)),
    ("square", np.square), ("abs", np.abs), ("floor", np.floor),
    ("ceil", np.ceil), ("round", np.round), ("sign", np.sign),
    ("sin", np.sin), ("cos", np.cos), ("tan", np.tan),
    ("sinh", np.sinh), ("cosh", np.cosh),
    ("reciprocal", lambda x: 1.0 / (x + 3.0)),
    ("rsqrt", lambda x: 1.0 / np.sqrt(np.abs(x) + 2.5)),
    ("erf", None), ("logsigmoid", None), ("gelu", None), ("relu6", None),
    ("selu", None), ("mish", None), ("silu", None), ("swish", None),
    ("softplus", None), ("softsign", None), ("hard_swish", None),
]


@pytest.mark.parametrize("name,ref", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_tail(name, ref, _fresh):
    x = L.data("x", [4])
    # ops with domain restrictions get shifted inputs inside ref; feed the
    # shifted value instead for those
    feed = X
    if name in ("log", "sqrt", "rsqrt"):
        feed = np.abs(X) + 2.5
        ref_fn = {"log": np.log, "sqrt": np.sqrt,
                  "rsqrt": lambda v: 1.0 / np.sqrt(v)}[name]
    elif name == "reciprocal":
        feed = X + 3.0
        ref_fn = lambda v: 1.0 / v
    elif ref is not None:
        ref_fn = ref
    else:
        ref_fn = None
    out = getattr(L, name)(x)
    got, = _run(_fresh, {"x": feed}, [out])
    if ref_fn is not None:
        np.testing.assert_allclose(got, ref_fn(feed), rtol=1e-5, atol=1e-6)
    else:
        assert got.shape == feed.shape and np.isfinite(got).all()


def test_parametrized_activations(_fresh):
    x = L.data("x", [4])
    la = L.leaky_relu(x, alpha=0.1)
    el = L.elu(x, alpha=0.5)
    hs = L.hard_sigmoid(x)
    ls = L.log_softmax(x)
    pw = L.pow(x, factor=3.0)
    r = _run(_fresh, {"x": X}, [la, el, hs, ls, pw])
    np.testing.assert_allclose(r[0], np.where(X >= 0, X, 0.1 * X), rtol=1e-6)
    np.testing.assert_allclose(r[1], np.where(X >= 0, X, 0.5 * (np.exp(X) - 1)),
                               rtol=1e-5)
    np.testing.assert_allclose(r[3], np.asarray(
        jax.nn.log_softmax(jnp.asarray(X), axis=-1)), rtol=1e-5)
    np.testing.assert_allclose(r[4], X ** 3, rtol=1e-5)


def test_shape_index_tail(_fresh):
    x = L.data("x", [4])
    idx = L.data("idx", [-1], dtype="int64", append_batch_size=False)
    sh = L.shape(x)
    sq = L.squeeze(L.unsqueeze(x, [1]), ())
    st = L.stack([x, x], axis=0)
    ex = L.expand(L.unsqueeze(x, [0]), [2, -1, -1])
    tl = L.tile(x, [2, 1])
    sl = L.slice(x, axes=[1], starts=[1], ends=[3])
    g = L.gather(x, idx, axis=0)
    oh = L.one_hot(idx, depth=5)
    cs = L.cumsum(x, axis=1)
    feeds = {"x": X, "idx": np.array([2, 0], np.int64)}
    r = _run(_fresh, feeds, [sh, sq, st, ex, tl, sl, g, oh, cs])
    np.testing.assert_array_equal(r[0], [3, 4])
    np.testing.assert_allclose(r[1], X)
    np.testing.assert_allclose(r[2], np.stack([X, X]))
    np.testing.assert_allclose(r[3], np.broadcast_to(X[None], (2, 3, 4)))
    np.testing.assert_allclose(r[4], np.tile(X, (2, 1)))
    np.testing.assert_allclose(r[5], X[:, 1:3])
    np.testing.assert_allclose(r[6], X[[2, 0]])
    np.testing.assert_allclose(r[7], np.eye(5)[[2, 0]])
    np.testing.assert_allclose(r[8], np.cumsum(X, axis=1), rtol=1e-6)


def test_where_scatter_gather_nd(_fresh):
    x = L.data("x", [4])
    y = L.data("y", [4])
    cond = static.greater_than(x, y)
    w = L.where(cond, x, y)
    Y = -X
    r = _run(_fresh, {"x": X, "y": Y}, [w])
    np.testing.assert_allclose(r[0], np.where(X > Y, X, Y))


def test_loss_tail(_fresh):
    x = L.data("x", [4])
    lbl = L.data("lbl", [4])
    sce = L.sigmoid_cross_entropy_with_logits(x, lbl)
    hub = L.huber_loss(x, lbl, delta=0.5)
    sl1 = L.smooth_l1(x, lbl)
    mse = L.mse_loss(x, lbl)
    P = 1.0 / (1.0 + np.exp(-X))
    LBL = (P > 0.5).astype(np.float32)
    r = _run(_fresh, {"x": X, "lbl": LBL}, [sce, hub, sl1, mse])
    ref_sce = np.maximum(X, 0) - X * LBL + np.log1p(np.exp(-np.abs(X)))
    np.testing.assert_allclose(r[0], ref_sce, rtol=1e-5)
    np.testing.assert_allclose(r[3], np.mean((X - LBL) ** 2), rtol=1e-5)
    assert np.isfinite(r[1]).all() and np.isfinite(r[2]).all()


def test_log_loss_label_smooth_l2norm_kldiv(_fresh):
    p = L.data("p", [4])
    lbl = L.data("lbl", [4])
    ll = L.log_loss(p, lbl, epsilon=1e-4)
    ls = L.label_smooth(lbl, epsilon=0.2)
    l2 = L.l2_normalize(p, axis=-1)
    kd = L.kldiv_loss(L.log_softmax(p), lbl, reduction="mean")
    P = np.clip(np.abs(X) / 3.0, 0.05, 0.95)
    LBL = np.ones_like(P) / 4.0
    r = _run(_fresh, {"p": P, "lbl": LBL}, [ll, ls, l2, kd])
    np.testing.assert_allclose(
        r[0], -LBL * np.log(P + 1e-4) - (1 - LBL) * np.log(1 - P + 1e-4),
        rtol=1e-5)
    np.testing.assert_allclose(r[1], 0.8 * LBL + 0.2 / 4.0, rtol=1e-5)
    np.testing.assert_allclose(
        r[2], P / np.sqrt((P ** 2).sum(-1, keepdims=True)), rtol=1e-5)
    assert np.isfinite(r[3]).all()


def test_layer_norm_dsl_trains(_fresh):
    x = L.data("x", [4])
    h = L.layer_norm(L.fc(x, 8), begin_norm_axis=1)
    loss = L.mean(L.square(h))
    opt = static.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    l0, = exe.run(_fresh, feed={"x": X}, fetch_list=[loss])
    assert np.isfinite(float(l0))


def test_elementwise_max_min_pow(_fresh):
    x = L.data("x", [4])
    y = L.data("y", [4])
    mx = L.elementwise_max(x, y)
    mn = L.elementwise_min(x, y)
    Y = -X
    r = _run(_fresh, {"x": X, "y": Y}, [mx, mn])
    np.testing.assert_allclose(r[0], np.maximum(X, Y))
    np.testing.assert_allclose(r[1], np.minimum(X, Y))
