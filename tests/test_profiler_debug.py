"""Profiler API, monitor, and NaN/Inf debugging."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pd
from paddle_tpu.utils import check_numerics, debug, monitor, profiler


def test_profiler_context_and_timeline(tmp_path, capsys):
    profiler.reset_profiler()
    path = str(tmp_path / "timeline.json")
    with profiler.profiler(profile_path=path):
        with profiler.RecordEvent("forward"):
            x = jnp.ones((8, 8))
            (x @ x).block_until_ready()
        with profiler.RecordEvent("backward"):
            pass
    out = capsys.readouterr().out
    assert "forward" in out and "Calls" in out
    with open(path) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    assert {"forward", "backward"} <= names


def test_record_event_decorator(tmp_path):
    profiler.reset_profiler()
    profiler.start_profiler()

    @profiler.RecordEvent("decorated_fn")
    def fn(a, b):
        return a + b

    assert fn(1, 2) == 3
    profiler.stop_profiler()
    assert "decorated_fn" in profiler.summary()


def test_summary_sorted_key_orders_rows():
    """Regression: sorted_key was accepted and ignored (fluid API contract:
    total|calls|max|min|ave, descending)."""
    from paddle_tpu.core import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    profiler.reset_profiler()
    profiler.start_profiler()
    ms = 1_000_000
    for _ in range(3):
        native.prof_add_span("many_short", 0, 1 * ms)
    native.prof_add_span("one_long", 0, 500 * ms)
    try:
        def first_row(key):
            return profiler.summary(key).splitlines()[1].split()[0]

        assert first_row("total") == "one_long"
        assert first_row("max") == "one_long"
        assert first_row(None) == "one_long"  # default stays total-sorted
        assert first_row("calls") == "many_short"
        assert first_row("min") == "one_long"  # descending: largest min first
        with pytest.raises(ValueError, match="sorted_key"):
            profiler.summary("bogus")
    finally:
        profiler.stop_profiler(sorted_key="calls")


def test_stop_profiler_prints_sorted_table(capsys):
    from paddle_tpu.core import native

    if not native.available():
        pytest.skip("native runtime unavailable")
    profiler.reset_profiler()
    profiler.start_profiler()
    ms = 1_000_000
    for _ in range(5):
        native.prof_add_span("frequent", 0, 1 * ms)
    native.prof_add_span("slow", 0, 900 * ms)
    profiler.stop_profiler(sorted_key="calls")
    lines = capsys.readouterr().out.splitlines()
    assert lines[1].startswith("frequent"), lines[:3]


def test_chrome_trace_merges_counter_samples(tmp_path):
    from paddle_tpu.utils import monitor

    profiler.reset_profiler()
    monitor.counter("pytest.chrome_counter", "merged into traces").inc(4)
    profiler.start_profiler()
    with profiler.RecordEvent("span_for_chrome"):
        pass
    profiler.stop_profiler()
    path = str(tmp_path / "merged.json")
    profiler.export_chrome_tracing(path)
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    counters = {e["name"]: e["args"]["value"]
                for e in events if e.get("ph") == "C"}
    assert counters.get("pytest.chrome_counter", 0) >= 4
    spans = {e["name"] for e in events if e.get("ph") == "X"}
    if spans:  # native side present: spans and counters share one timeline
        assert "span_for_chrome" in spans


def test_monitor_stats():
    monitor.stat_reset("pytest.gauge")
    monitor.stat_add("pytest.gauge", 5)
    assert monitor.stat_get("pytest.gauge") == 5
    assert monitor.stats()["pytest.gauge"] == 5


def test_check_numerics_flags_nan_in_jit():
    debug.enable_nan_check(eager_also=False)
    try:
        @jax.jit
        def f(x):
            y = {"a": x, "b": jnp.log(x)}  # log(-1) -> nan
            return check_numerics(y, "activations")

        # under jit the callback's FloatingPointError surfaces wrapped in
        # JaxRuntimeError; the message (incl. the bad leaf path) is preserved
        with pytest.raises(Exception, match="NaN/Inf detected in 'activations'"):
            jax.block_until_ready(f(jnp.array([-1.0])))
        # clean values pass
        out = jax.block_until_ready(f(jnp.array([1.0])))
        assert float(out["a"][0]) == 1.0
    finally:
        debug.disable_nan_check()


def test_check_numerics_noop_when_disabled():
    debug.disable_nan_check()
    out = check_numerics({"a": jnp.array([jnp.inf])}, "x")
    assert not np.isfinite(float(out["a"][0]))  # passed through, no raise


def test_check_numerics_force_names_bad_leaf():
    with pytest.raises(FloatingPointError, match="b"):
        jax.block_until_ready(
            check_numerics({"a": jnp.ones(2), "b": jnp.array([np.nan])},
                           "grads", force=True))
