"""Reference binary-model interop (static/proto_format.py).

Round-4 VERDICT missing #1: a `__model__` saved by the reference's
save_inference_model must load and serve here.  Coverage: a GOLDEN
hand-encoded fixture (decoder validated independently of our encoder),
encoder round-trips on two book models with numerics matched against the
native json path, combined `__params__` files, and LoDTensor dtype
round-trips."""
import struct

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers as L
from paddle_tpu.static import proto_format as PF


@pytest.fixture(autouse=True)
def _fresh_programs():
    main, startup = static.Program(), static.Program()
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        yield main, startup


# -- golden fixture: bytes written by hand from framework.proto ---------------

def _varint(v):
    v &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            return bytes(out)


def _ld(num, payload):  # length-delimited field
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _vi(num, v):        # varint field
    return _varint(num << 3) + _varint(v)


def _golden_model_bytes():
    """ProgramDesc for:  out = scale(x, 2.5) + bias_w  — one feed var
    `x` (fp32, [-1, 3]), one persistable `bias_w` (fp32 [3]), feed/fetch
    ops, encoded field-by-field from framework.proto (NOT via our
    encoder)."""
    def tensor_desc(data_type, dims):
        body = _vi(1, data_type)
        for d in dims:
            body += _vi(2, d)
        return body

    def lod_var(name, data_type, dims, persistable):
        vt = _vi(1, 7) + _ld(3, _ld(1, tensor_desc(data_type, dims))
                             + _vi(2, 0))
        body = _ld(1, name.encode()) + _ld(2, vt)
        if persistable:
            body += _vi(3, 1)
        return body

    def raw_var(name, type_code):
        return _ld(1, name.encode()) + _ld(2, _vi(1, type_code)) + _vi(3, 1)

    def opvar(num, slot, args):
        body = _ld(1, slot.encode())
        for a in args:
            body += _ld(2, a.encode())
        return _ld(num, body)

    def attr_f(name, value):  # FLOAT attr
        return _ld(1, name.encode()) + _vi(2, 1) \
            + _varint((4 << 3) | 5) + struct.pack("<f", value)

    def attr_i(name, value):  # INT attr
        return _ld(1, name.encode()) + _vi(2, 0) + _vi(3, value)

    feed_op = opvar(1, "X", ["feed"]) + opvar(2, "Out", ["x"]) \
        + _ld(3, b"feed") + _ld(4, attr_i("col", 0))
    scale_op = opvar(1, "X", ["x"]) + opvar(2, "Out", ["scaled"]) \
        + _ld(3, b"scale") + _ld(4, attr_f("scale", 2.5)) \
        + _ld(4, attr_f("bias", 0.0))
    add_op = opvar(1, "X", ["scaled"]) + opvar(1, "Y", ["bias_w"]) \
        + opvar(2, "Out", ["out"]) + _ld(3, b"elementwise_add") \
        + _ld(4, attr_i("axis", -1))
    fetch_op = opvar(1, "X", ["out"]) + opvar(2, "Out", ["fetch"]) \
        + _ld(3, b"fetch") + _ld(4, attr_i("col", 0))

    block = _vi(1, 0) + _vi(2, 0)
    for v in [raw_var("feed", 9), raw_var("fetch", 10),
              lod_var("x", 5, [(1 << 64) - 1, 3], False),  # -1 batch dim
              lod_var("bias_w", 5, [3], True),
              lod_var("scaled", 5, [(1 << 64) - 1, 3], False),
              lod_var("out", 5, [(1 << 64) - 1, 3], False)]:
        block += _ld(3, v)
    for op in [feed_op, scale_op, add_op, fetch_op]:
        block += _ld(4, op)
    return _ld(1, block) + _ld(4, _vi(1, 0))


def test_golden_model_decodes_and_runs(tmp_path, _fresh_programs):
    model_dir = tmp_path / "golden"
    model_dir.mkdir()
    (model_dir / "__model__").write_bytes(_golden_model_bytes())
    bias = np.array([1.0, -2.0, 3.0], np.float32)
    with open(model_dir / "bias_w", "wb") as f:
        PF.write_lod_tensor(f, bias)

    exe = static.Executor()
    prog, feeds, fetches = static.load_inference_model(str(model_dir), exe)
    assert feeds == ["x"] and fetches == ["out"]
    x = np.array([[1.0, 2.0, 3.0], [0.0, 0.5, -1.0]], np.float32)
    out, = exe.run(prog, feed={"x": x}, fetch_list=fetches)
    np.testing.assert_allclose(out, 2.5 * x + bias, rtol=1e-6)


def test_golden_decoder_fields():
    desc = PF.parse_program_desc(_golden_model_bytes())
    blk = desc["blocks"][0]
    assert [op["type"] for op in blk["ops"]] == [
        "feed", "scale", "elementwise_add", "fetch"]
    scale = blk["ops"][1]
    assert scale["attrs"]["scale"] == pytest.approx(2.5)
    xvar = next(v for v in blk["vars"] if v["name"] == "x")
    assert xvar["type"]["tensor"]["dims"] == [-1, 3]       # signed varint
    assert not xvar["persistable"]
    assert next(v for v in blk["vars"]
                if v["name"] == "bias_w")["persistable"]


# -- round trips on two book models ------------------------------------------

def _train_fit_a_line(main, startup):
    x = L.data("x", [13])
    y_predict = L.fc(x, 1, act=None)
    y = L.data("y", [1])
    avg_cost = L.mean(L.square_error_cost(y_predict, y))
    static.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (64, 13)).astype(np.float32)
    Y = rng.normal(0, 1, (64, 1)).astype(np.float32)
    exe = static.Executor()
    exe.run(startup)
    for _ in range(3):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg_cost])
    return exe, y_predict, X


def test_fit_a_line_proto_roundtrip(tmp_path, _fresh_programs):
    main, startup = _fresh_programs
    exe, y_predict, X = _train_fit_a_line(main, startup)
    probe = X[:8]

    json_dir, proto_dir = str(tmp_path / "json"), str(tmp_path / "proto")
    static.save_inference_model(json_dir, ["x"], [y_predict], exe)
    static.save_inference_model(proto_dir, ["x"], [y_predict], exe,
                                model_filename="__model__")

    pj, feeds_j, fetch_j = static.load_inference_model(json_dir, exe)
    pred_json, = exe.run(pj, feed={"x": probe}, fetch_list=fetch_j)
    pp, feeds_p, fetch_p = static.load_inference_model(proto_dir, exe)
    assert feeds_p == feeds_j == ["x"]
    assert fetch_p == fetch_j
    pred_proto, = exe.run(pp, feed={"x": probe}, fetch_list=fetch_p)
    np.testing.assert_allclose(pred_proto, pred_json, rtol=1e-6)


def test_word2vec_style_proto_roundtrip_combined_params(tmp_path,
                                                        _fresh_programs):
    """Second book model (word2vec shape: shared embedding + fc stack),
    with the combined `__params__` single-file layout."""
    main, startup = _fresh_programs
    words = [L.data(n, [1], dtype="int64")
             for n in ("firstw", "secondw", "thirdw", "forthw")]
    embeds = [L.embedding(w, size=[32, 16], param_attr="shared_w")
              for w in words]
    concat = L.concat(embeds, axis=1)
    hidden = L.fc(concat, 64, act="sigmoid")
    predict = L.fc(hidden, 32, act="softmax")

    exe = static.Executor()
    exe.run(startup)
    rng = np.random.default_rng(3)
    feed = {n: rng.integers(0, 32, (8, 1)).astype(np.int64)
            for n in ("firstw", "secondw", "thirdw", "forthw")}

    proto_dir = str(tmp_path / "proto")
    static.save_inference_model(
        proto_dir, list(feed), [predict], exe,
        model_filename="__model__", params_filename="__params__")
    import os

    assert os.path.exists(os.path.join(proto_dir, "__params__"))
    assert not os.path.exists(os.path.join(proto_dir, "shared_w"))

    ref, = exe.run(main, feed=feed, fetch_list=[predict])
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        pp, feeds_p, fetch_p = static.load_inference_model(
            proto_dir, exe, params_filename="__params__")
        out, = exe.run(pp, feed=feed, fetch_list=fetch_p, scope=scope2)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_lod_tensor_dtype_roundtrip(tmp_path):
    import io as _io

    for arr in [np.arange(6, dtype=np.float32).reshape(2, 3),
                np.arange(4, dtype=np.int64),
                np.array([[1, 0], [0, 1]], np.bool_),
                np.arange(3, dtype=np.float64),
                np.array([1.5, -2.5], np.float16)]:
        buf = _io.BytesIO()
        PF.write_lod_tensor(buf, arr)
        buf.seek(0)
        back = PF.read_lod_tensor(buf)
        assert back.dtype == arr.dtype and back.shape == arr.shape
        np.testing.assert_array_equal(back, arr)


def test_reader_skips_lod_payload(tmp_path):
    """A reference file with real LoD levels still parses (offsets are
    meaningless under the dense layout and are skipped)."""
    import io as _io

    arr = np.arange(5, dtype=np.float32)
    buf = _io.BytesIO()
    buf.write(struct.pack("<I", 0))
    buf.write(struct.pack("<Q", 1))                    # one lod level
    offs = np.array([0, 2, 5], np.uint64)
    buf.write(struct.pack("<Q", offs.nbytes))
    buf.write(offs.tobytes())
    buf.write(struct.pack("<I", 0))
    desc = PF._enc_tensor_desc({"data_type": 5, "dims": [5]})
    buf.write(struct.pack("<i", len(desc)))
    buf.write(desc)
    buf.write(arr.tobytes())
    buf.seek(0)
    np.testing.assert_array_equal(PF.read_lod_tensor(buf), arr)


def test_unknown_op_gives_actionable_error(tmp_path, _fresh_programs):
    desc = PF.parse_program_desc(_golden_model_bytes())
    desc["blocks"][0]["ops"][1]["type"] = "tensorrt_engine"
    from paddle_tpu.core.errors import UnimplementedError

    with pytest.raises(UnimplementedError, match="op_coverage"):
        PF.program_from_desc(desc)


def test_reference_save_removes_stale_native_files(tmp_path,
                                                   _fresh_programs):
    """Saving the reference format over a dir that held the native format
    must not leave program.json to win load auto-detection."""
    import os

    main, startup = _fresh_programs
    exe, y_predict, X = _train_fit_a_line(main, startup)
    d = str(tmp_path / "m")
    static.save_inference_model(d, ["x"], [y_predict], exe)
    assert os.path.exists(os.path.join(d, "program.json"))
    static.save_inference_model(d, ["x"], [y_predict], exe,
                                model_filename="__model__")
    assert not os.path.exists(os.path.join(d, "program.json"))
    assert not os.path.exists(os.path.join(d, "params.npz"))
    prog, feeds, fetches = static.load_inference_model(d, exe)
    out, = exe.run(prog, feed={"x": X[:4]}, fetch_list=fetches)
    assert out.shape == (4, 1)


def test_cipher_rejected_on_reference_format(tmp_path, _fresh_programs):
    from paddle_tpu.utils.crypto import Cipher

    main, startup = _fresh_programs
    exe, y_predict, X = _train_fit_a_line(main, startup)
    d = str(tmp_path / "m")
    cipher = Cipher(b"0" * 32)
    with pytest.raises(ValueError, match="cipher"):
        static.save_inference_model(d, ["x"], [y_predict], exe,
                                    cipher=cipher,
                                    model_filename="__model__")
    static.save_inference_model(d, ["x"], [y_predict], exe,
                                model_filename="__model__")
    with pytest.raises(ValueError, match="cipher"):
        static.load_inference_model(d, exe, cipher=cipher,
                                    model_filename="__model__")


# -- negative int attrs: canonical proto2 wire form ---------------------------

def test_negative_int_attr_encodes_sign_extended():
    """proto2 int32 fields encode negatives as 10-byte sign-extended
    varints — a truncated 5-byte form round-trips through OUR decoder but
    is rejected/misread by strict reference parsers (regression for the
    `& 0xFFFFFFFF` truncation in _enc_attr)."""
    body = PF._enc_attr("axis", PF.INT, -1)
    # field 3 varint payload must be the full 64-bit sign extension
    canonical = _varint((3 << 3)) + _varint((1 << 64) - 1)
    assert canonical in body
    name, atype, value = PF._parse_attr(body)
    assert (name, atype, value) == ("axis", PF.INT, -1)

    body = PF._enc_attr("shape", PF.INTS, [-1, 3, -7])
    assert _varint((6 << 3)) + _varint((1 << 64) - 1) in body
    name, atype, value = PF._parse_attr(body)
    assert (name, atype, value) == ("shape", PF.INTS, [-1, 3, -7])


def test_negative_int_attr_decoder_accepts_both_forms():
    """The decoder keeps accepting the legacy truncated 5-byte form (our
    own pre-fix files) alongside the canonical 10-byte one."""
    base = _ld(1, b"axis") + _vi(2, PF.INT)
    legacy = base + _varint(3 << 3) + _varint(-1 & 0xFFFFFFFF)
    canon = base + _varint(3 << 3) + _varint(-1 & ((1 << 64) - 1))
    assert PF._parse_attr(legacy) == ("axis", PF.INT, -1)
    assert PF._parse_attr(canon) == ("axis", PF.INT, -1)


def test_negative_int_attr_program_roundtrip(tmp_path, _fresh_programs):
    """End-to-end: a program whose op carries negative INT/INTS attrs
    (reshape shape=[-1, 2], elementwise axis=-1) survives
    program_to_desc -> encode -> parse -> program_from_desc."""
    main, _ = _fresh_programs
    x = L.data("x", [4])
    y = L.reshape(x, [-1, 2])
    blob = PF.encode_program_desc(PF.program_to_desc(main, ["x"], [y.name]))
    desc = PF.parse_program_desc(blob)
    shapes = [op["attrs"]["shape"] for b in desc["blocks"]
              for op in b["ops"] if "shape" in op["attrs"]]
    assert [-1, 2] in shapes
    prog, feeds, fetches = PF.program_from_desc(desc)
    assert feeds == ["x"]


# -- multi-block export guard -------------------------------------------------

def test_program_to_desc_rejects_sub_block_ops(_fresh_programs):
    """Mirror of the import-side guard: exporting an op that carries a
    sub-block attr must fail legibly instead of silently dropping the
    cond/while body."""
    from paddle_tpu.core.errors import UnimplementedError

    main, _ = _fresh_programs
    x = L.data("x", [4])
    y = L.scale(x, scale=2.0)
    main.global_block().ops[-1].attrs["body_block"] = 1
    with pytest.raises(UnimplementedError, match="sub-block"):
        PF.program_to_desc(main, ["x"], [y.name])


def test_program_to_desc_rejects_multi_block(_fresh_programs):
    from paddle_tpu.core.errors import UnimplementedError

    main, _ = _fresh_programs
    x = L.data("x", [4])
    y = L.scale(x, scale=2.0)
    main.blocks.append(object())  # guard fires before any block is touched
    try:
        with pytest.raises(UnimplementedError, match="sub-block"):
            PF.program_to_desc(main, ["x"], [y.name])
    finally:
        main.blocks.pop()
