"""Training goodput watchdog (utils/watchdog.py): rolling-median/MAD
step-time anomalies, NaN/spiking-loss detection with flag-gated pre-emptive
checkpoints, flight-event goodput attribution, and cross-rank straggler
attribution over the elastic heartbeat dir."""
import json
import math
import os
import time

import numpy as np
import pytest

from paddle_tpu.core import flags
from paddle_tpu.elastic import checkpoint as eckpt
from paddle_tpu.utils import monitor, trace, watchdog as wd


@pytest.fixture
def _watchdog_flags_guard():
    saved = flags.get_flags(["watchdog", "watchdog_checkpoint_on_anomaly",
                             "elastic_ckpt_dir", "elastic_keep_last",
                             "metrics"])
    yield
    flags.set_flags(saved)


def _flight_since(seq):
    return trace.flight_recorder().events_since(seq)


# ---------------------------------------------------------------------------
# step-time anomaly detection (median + MAD)
# ---------------------------------------------------------------------------

def test_injected_5x_straggler_step_is_flagged():
    reg = monitor.default_registry()
    n0 = reg.get("watchdog.anomalies").value(kind="step_time")
    seq0 = trace.flight_recorder().last_seq
    w = wd.Watchdog(window=16, min_samples=8)
    for i in range(16):
        assert w.observe_step(i, 100.0 + (i % 5)) == []  # jittery but sane
    flagged = w.observe_step(16, 500.0)                  # the 5x straggler
    assert flagged == ["step_time"]
    assert reg.get("watchdog.anomalies").value(kind="step_time") - n0 == 1
    evs = [e for e in _flight_since(seq0)
           if e["kind"] == "watchdog_step_anomaly"]
    assert len(evs) == 1
    assert evs[0]["dur_ms"] == 500.0
    assert evs[0]["median_ms"] == pytest.approx(102.0, abs=2.0)
    # recovery: subsequent normal steps are not flagged (the outlier is in
    # the window now, but median/MAD shrug it off)
    assert w.observe_step(17, 101.0) == []
    rep = w.report()
    assert rep["anomalies"]["step_time"] == 1
    assert rep["last_anomaly"]["kind"] == "step_time"
    assert rep["healthy"]  # step-time anomalies degrade, NaN loss unhealths


def test_steady_series_never_flags_and_needs_min_samples():
    w = wd.Watchdog(min_samples=8)
    # before min_samples, even a wild value passes (no baseline yet)
    assert w.observe_step(0, 1.0) == []
    assert w.observe_step(1, 900.0) == []
    w2 = wd.Watchdog(min_samples=4)
    for i in range(50):
        assert w2.observe_step(i, 10.0) == []


def test_rolling_median_mad_reference():
    med, mad = wd.rolling_median_mad([1.0, 2.0, 3.0, 4.0, 100.0])
    assert med == 3.0 and mad == 1.0          # robust to the outlier
    med2, mad2 = wd.rolling_median_mad([5.0, 7.0])
    assert med2 == 6.0 and mad2 == 1.0
    assert all(math.isnan(v) for v in wd.rolling_median_mad([]))


# ---------------------------------------------------------------------------
# loss health: NaN + spike, flag-gated pre-emptive checkpoint
# ---------------------------------------------------------------------------

def test_nan_loss_flight_event_and_gated_checkpoint(_watchdog_flags_guard):
    calls = []
    seq0 = trace.flight_recorder().last_seq
    w = wd.Watchdog(checkpoint_fn=lambda reason: calls.append(reason))
    # flag off: detected + flight-recorded, but NOT checkpointed
    flags.set_flags({"watchdog_checkpoint_on_anomaly": False})
    assert w.observe_step(0, 10.0, loss=float("nan")) == ["nan_loss"]
    assert calls == []
    # flag on: the next anomaly checkpoints (once — max_anomaly_checkpoints)
    flags.set_flags({"watchdog_checkpoint_on_anomaly": True})
    reg = monitor.default_registry()
    c0 = reg.get("watchdog.checkpoints").value()
    assert w.observe_step(1, 10.0, loss=float("inf")) == ["nan_loss"]
    assert calls == ["nan_loss"]
    assert reg.get("watchdog.checkpoints").value() - c0 == 1
    assert w.observe_step(2, 10.0, loss=float("nan")) == ["nan_loss"]
    assert calls == ["nan_loss"]  # budget spent, no second save
    kinds = [e["kind"] for e in _flight_since(seq0)]
    assert kinds.count("watchdog_nan_loss") == 3
    assert kinds.count("watchdog_checkpoint") == 1
    assert not w.report()["healthy"]


def test_loss_spike_detected_against_rolling_median():
    w = wd.Watchdog(min_samples=4, loss_spike_factor=10.0)
    for i in range(8):
        assert w.observe_step(i, 10.0, loss=0.5 + 0.01 * i) == []
    assert w.observe_step(8, 10.0, loss=50.0) == ["loss_spike"]
    # a failing checkpoint_fn is flight-recorded, never raised
    seq0 = trace.flight_recorder().last_seq
    w2 = wd.Watchdog(checkpoint_fn=lambda r: 1 / 0)
    flags.set_flags({"watchdog_checkpoint_on_anomaly": True})
    try:
        assert w2.observe_step(0, 1.0, loss=float("nan")) == ["nan_loss"]
    finally:
        flags.set_flags({"watchdog_checkpoint_on_anomaly": False})
    assert any(e["kind"] == "watchdog_checkpoint_failed"
               for e in _flight_since(seq0))


# ---------------------------------------------------------------------------
# goodput attribution off the flight ring
# ---------------------------------------------------------------------------

def test_goodput_attribution_buckets_flight_events():
    w = wd.Watchdog()
    fr = trace.flight_recorder()
    # synthetic executor/elastic events land in the ring after the cursor
    fr.record("span_end", name="executor::trace_compile", dur_ms=40.0)
    fr.record("elastic_restore", name="step5", dur_ms=25.0)
    fr.record("elastic_checkpoint", name="step6", dur_ms=10.0)
    w.observe_step(0, 30.0)
    rep = w.report()
    assert rep["time_ms"]["compile"] == pytest.approx(40.0)
    assert rep["time_ms"]["restore"] == pytest.approx(25.0)
    assert rep["time_ms"]["checkpoint"] == pytest.approx(10.0)
    assert rep["time_ms"]["productive"] == pytest.approx(30.0)
    assert 0.0 < rep["goodput_pct"] <= 100.0
    # the cursor advanced: re-observing must not double-count
    w.observe_step(1, 30.0)
    assert w.report()["time_ms"]["compile"] == pytest.approx(40.0)
    # exported as gauge + cumulative per-category counter
    reg = monitor.default_registry()
    assert isinstance(reg.get("train.goodput_pct").value(), float)
    assert reg.get("watchdog.time_ms").value(category="productive") > 0


def test_goodput_pct_reflects_productive_fraction():
    w = wd.Watchdog()
    w._t_start = time.time() - 1.0          # pretend 1s of wall clock
    w.observe_step(0, 600.0)                # 600ms productive
    assert w.goodput_pct() == pytest.approx(60.0, abs=15.0)


# ---------------------------------------------------------------------------
# cross-rank straggler attribution over the heartbeat dir
# ---------------------------------------------------------------------------

def _write_hb(directory, rank, step, ts=None):
    with open(os.path.join(directory, f"hb.{rank}.json"), "w") as f:
        json.dump({"rank": rank, "pid": 1000 + rank, "step": step,
                   "ts": time.time() if ts is None else ts}, f)


def test_two_rank_straggler_attribution(tmp_path):
    d = str(tmp_path)
    _write_hb(d, 0, 100)
    _write_hb(d, 1, 40)                     # 60 steps behind
    seq0 = trace.flight_recorder().last_seq
    w = wd.Watchdog(heartbeat_dir=d)
    rep = w.straggler_report()
    assert rep["front_step"] == 100
    assert rep["stragglers"] == [1]
    assert rep["ranks"]["1"]["lag"] == 60
    assert rep["ranks"]["0"]["lag"] == 0
    evs = [e for e in _flight_since(seq0)
           if e["kind"] == "watchdog_straggler"]
    assert len(evs) == 1 and evs[0]["worker"] == 1
    # the report also rides /healthz via report()
    assert w.report()["stragglers"]["stragglers"] == [1]


def test_near_uniform_ranks_not_flagged(tmp_path):
    d = str(tmp_path)
    for r, s in ((0, 100), (1, 99), (2, 97), (3, 100)):
        _write_hb(d, r, s)
    w = wd.Watchdog(heartbeat_dir=d)
    assert w.straggler_report()["stragglers"] == []
    # no heartbeat dir -> empty report, never a crash
    assert wd.Watchdog().straggler_report() == {"ranks": {},
                                                "stragglers": []}


# ---------------------------------------------------------------------------
# hapi wiring: watchdog flag -> callback -> NaN fixture checkpoints
# ---------------------------------------------------------------------------

def _hapi_model(seed=5):
    import paddle_tpu as pd
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model

    pd.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = Model(net)
    model.prepare(optimizer=pd.optimizer.SGD(learning_rate=0.05),
                  loss=nn.MSELoss())
    return model


def _nan_data():
    from paddle_tpu.io import TensorDataset

    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.normal(size=(64, 1)).astype(np.float32)
    y[:] = np.nan                           # poisoned labels -> NaN loss
    return TensorDataset([x, y])


def test_fit_nan_loss_flight_event_and_preemptive_checkpoint(
        tmp_path, _watchdog_flags_guard):
    ckpt = str(tmp_path / "wd_ckpt")
    flags.set_flags({"watchdog": True,
                     "watchdog_checkpoint_on_anomaly": True,
                     "elastic_ckpt_dir": ckpt})
    seq0 = trace.flight_recorder().last_seq
    model = _hapi_model()
    model.fit(_nan_data(), batch_size=16, epochs=1, verbose=0)
    kinds = [e["kind"] for e in _flight_since(seq0)]
    assert "watchdog_nan_loss" in kinds
    assert "watchdog_checkpoint" in kinds
    # the pre-emptive elastic checkpoint is real and restorable
    steps = eckpt.list_steps(ckpt)
    assert len(steps) == 1                   # max_anomaly_checkpoints=1
    body = eckpt.load_manifest(ckpt)
    names = [l["name"] for l in body["leaves"]]
    assert any(n.startswith("param/") for n in names)
    assert any(n.startswith("opt/") for n in names)


def test_fit_healthy_run_no_anomalies(tmp_path, _watchdog_flags_guard):
    from paddle_tpu.io import TensorDataset

    flags.set_flags({"watchdog": True,
                     "watchdog_checkpoint_on_anomaly": False,
                     "elastic_ckpt_dir": str(tmp_path / "nope")})
    rng = np.random.default_rng(0)
    data = TensorDataset([rng.normal(size=(64, 8)).astype(np.float32),
                          rng.normal(size=(64, 1)).astype(np.float32)])
    seq0 = trace.flight_recorder().last_seq
    model = _hapi_model()
    model.fit(data, batch_size=16, epochs=1, verbose=0)
    assert not any(e["kind"].startswith("watchdog_")
                   for e in _flight_since(seq0))
    assert not (tmp_path / "nope").exists()


def test_watchdog_callback_direct_and_lazy_logs():
    cb = wd.WatchdogCallback(watchdog=wd.Watchdog(min_samples=4))
    cb.on_train_begin()
    for i in range(6):
        cb.on_train_batch_begin(i)
        cb.on_train_batch_end(i, {"loss": 0.5})
    assert cb.watchdog.report()["steps"] == 6
    # batch_end without batch_begin (resumed loop) is a no-op, not a crash
    cb.on_train_batch_end(99, {"loss": 0.5})
    assert cb.watchdog.report()["steps"] == 6
    # the callback registered the watchdog as a /healthz provider
    from paddle_tpu.utils import telemetry

    assert telemetry._health_providers["watchdog"]()["steps"] == 6
