"""Text dataset zoo (synthetic/hermetic mode) + DataLoader integration."""
import numpy as np

from paddle_tpu.io import DataLoader
from paddle_tpu.text import Imdb, Imikolov, UCIHousing


def test_imdb_shapes_and_determinism():
    ds = Imdb(mode="train", maxlen=64, synthetic_size=32)
    seq, label = ds[0]
    assert seq.shape == (64,) and seq.dtype == np.int64
    assert label in (0, 1)
    ds2 = Imdb(mode="train", maxlen=64, synthetic_size=32)
    np.testing.assert_array_equal(ds[5][0], ds2[5][0])
    # train/test draw different corpora
    ds_test = Imdb(mode="test", maxlen=64, synthetic_size=32)
    assert not all(np.array_equal(ds[i][0], ds_test[i][0]) for i in range(5))


def test_imdb_learnable_signal():
    ds = Imdb(mode="train", maxlen=32, synthetic_size=64)
    # class-dependent vocab halves: mean token id differs by label
    mean_by_label = {0: [], 1: []}
    for i in range(len(ds)):
        seq, label = ds[i]
        mean_by_label[int(label)].append(seq[seq > 0].mean())
    assert np.mean(mean_by_label[1]) > np.mean(mean_by_label[0])


def test_imikolov_ngram_windows():
    ds = Imikolov(window_size=5, synthetic_size=128)
    ctx, nxt = ds[0]
    assert ctx.shape == (4,)
    ctx1, _ = ds[1]
    np.testing.assert_array_equal(ctx[1:], ctx1[:3])  # sliding window


def test_uci_housing_split_and_loader():
    train = UCIHousing(mode="train")
    test = UCIHousing(mode="test")
    assert train.features.shape[1] == 13
    assert len(train) > len(test) > 0
    loader = DataLoader(train, batch_size=16, shuffle=True, drop_last=True)
    xb, yb = next(iter(loader))
    assert np.asarray(xb).shape == (16, 13)
    assert np.asarray(yb).shape == (16, 1)
