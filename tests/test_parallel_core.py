"""Parallel core: mesh building, collectives (eager + traced), sharding rules,
fleet strategy composition.  Runs on the 8-device virtual CPU mesh (conftest)
— the rebuild's analogue of the reference's multi-process-on-localhost
distributed tests (test_collective_base.py, SURVEY.md §4.3)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec
from paddle_tpu.parallel.collective import shard_map

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu.parallel import (
    MeshConfig, ShardingRules, collective, infer_sharding, mesh as mesh_mod,
    shard_layer, shard_params,
)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_mod.set_mesh(None)


def test_build_mesh_shapes():
    m = mesh_mod.build_mesh(MeshConfig(dp=2, tp=4))
    assert m.axis_names == ("dp", "tp") and m.shape == {"dp": 2, "tp": 4}
    m = mesh_mod.build_mesh(MeshConfig())  # all-dp default
    assert m.shape["dp"] == 8
    m = mesh_mod.build_mesh(MeshConfig(dp=-1, pp=2, tp=2))
    assert m.shape == {"dp": 2, "pp": 2, "tp": 2}
    with pytest.raises(ValueError):
        mesh_mod.build_mesh(MeshConfig(dp=3, tp=4))


def test_init_parallel_env_sets_global():
    m = dist.init_parallel_env(tp=2)
    assert mesh_mod.current_mesh() is m
    assert mesh_mod.mesh_axis_size("tp") == 2
    assert mesh_mod.mesh_axis_size("dp") == 4


def test_all_reduce_eager_sharded():
    from jax.sharding import NamedSharding
    m = dist.init_parallel_env()
    # Per-rank semantics follow the input's actual placement: sharded input
    # -> each rank contributes its shard.
    x = jax.device_put(jnp.arange(8.0), NamedSharding(m, PartitionSpec("dp")))
    out = dist.all_reduce(x)
    np.testing.assert_allclose(np.asarray(out), np.full((1,), 28.0))
    # Replicated input -> every rank holds x, sum = world_size * x.
    y = dist.all_reduce(jnp.ones(3))
    np.testing.assert_allclose(np.asarray(y), np.full((3,), 8.0))


def test_all_reduce_traced_psum():
    m = dist.init_parallel_env(tp=2)

    def f(x):
        return collective.all_reduce(x, group="tp")

    g = shard_map(f, mesh=m, in_specs=(PartitionSpec("tp"),),
                  out_specs=PartitionSpec("tp"), check_rep=False)
    x = jnp.arange(4.0)
    out = g(x)  # two tp shards [0,1],[2,3] -> each psums to [2,4]
    np.testing.assert_allclose(np.asarray(out), [2., 4., 2., 4.])


def test_all_reduce_ops():
    m = dist.init_parallel_env(tp=2)

    def run(op):
        def f(x):
            return collective.all_reduce(x, op=op, group="tp")
        return shard_map(f, mesh=m, in_specs=(PartitionSpec("tp"),),
                         out_specs=PartitionSpec("tp"), check_rep=False)(
            jnp.array([1.0, 2.0, 3.0, 4.0]))

    np.testing.assert_allclose(np.asarray(run("max")), [3, 4, 3, 4])
    np.testing.assert_allclose(np.asarray(run("min")), [1, 2, 1, 2])
    np.testing.assert_allclose(np.asarray(run("avg")), [2, 3, 2, 3])
    np.testing.assert_allclose(np.asarray(run("prod")), [3, 8, 3, 8], rtol=1e-6)


def test_all_gather_traced_and_eager():
    m = dist.init_parallel_env(tp=4)

    def f(x):
        return collective.all_gather(x, group="tp")

    out = shard_map(f, mesh=m, in_specs=(PartitionSpec("tp"),),
                    out_specs=PartitionSpec(("dp", "tp")), check_rep=False)(
        jnp.arange(4.0))
    # every tp rank gathers the full [0..3]; dp=2 ranks each contribute a copy
    assert out.shape == (32,) or out.shape == (16,)

    from jax.sharding import NamedSharding
    x2 = jax.device_put(jnp.arange(8.0),
                        NamedSharding(m, PartitionSpec(("dp", "tp"))))
    out2 = dist.all_gather(x2)  # sharded input: gather-to-full
    np.testing.assert_allclose(np.asarray(out2), np.arange(8.0))


def test_reduce_scatter_traced():
    m = dist.init_parallel_env(tp=2)

    def f(x):
        return collective.reduce_scatter(x, group="tp")

    out = shard_map(f, mesh=m, in_specs=(PartitionSpec(None),),
                    out_specs=PartitionSpec("tp"), check_rep=False)(
        jnp.arange(4.0))
    # each rank holds replicated [0,1,2,3]; psum_scatter -> rank0 [0,2] rank1 [4,6]
    np.testing.assert_allclose(np.asarray(out), [0., 2., 4., 6.])


def test_broadcast_traced():
    m = dist.init_parallel_env(tp=2)

    def f(x):
        return collective.broadcast(x, src=1, group="tp")

    out = shard_map(f, mesh=m, in_specs=(PartitionSpec("tp"),),
                    out_specs=PartitionSpec("tp"), check_rep=False)(
        jnp.array([10.0, 20.0]))
    np.testing.assert_allclose(np.asarray(out), [20., 20.])


def test_all_to_all_traced():
    m = dist.init_parallel_env(tp=2)

    def f(x):
        return collective.all_to_all(x, group="tp", split_axis=0, concat_axis=1)

    x = jnp.arange(8.0).reshape(4, 2)  # per rank: (2,2) after tp split on dim0
    out = shard_map(f, mesh=m, in_specs=(PartitionSpec("tp", None),),
                    out_specs=PartitionSpec("tp", None), check_rep=False)(x)
    assert out.shape == (2, 4)


def test_scatter_and_barrier():
    dist.init_parallel_env()
    chunks = [jnp.full((2,), float(i)) for i in range(8)]
    out = dist.scatter(None, tensor_list=chunks, src=0)
    assert np.asarray(out).shape == (8, 2)
    dist.barrier()  # smoke


def test_group_registry():
    dist.init_parallel_env(tp=2)
    g = dist.new_group("tp")
    assert g.nranks == 2
    assert dist.get_group(g.id) is g
    g0 = dist.get_group(0)
    assert g0.size() == 8


def test_sharding_rules_and_infer():
    m = dist.init_parallel_env(tp=2)
    rules = ShardingRules([(r"w1$", (None, "tp")), (r"emb", ("tp", None))])
    params = {"w1": np.zeros((4, 8)), "emb": np.zeros((16, 4)),
              "b": np.zeros((5,)), "odd_w1": np.zeros((3, 3))}
    sh = infer_sharding(params, m, rules)
    assert sh["w1"].spec == PartitionSpec(None, "tp")
    assert sh["emb"].spec == PartitionSpec("tp")
    assert sh["b"].spec == PartitionSpec()
    assert sh["odd_w1"].spec == PartitionSpec()  # 3 not divisible by tp=2

    placed = shard_params(params, m, rules)
    assert placed["w1"].sharding.spec == PartitionSpec(None, "tp")


def test_zero_stage3_sharding():
    m = dist.init_parallel_env(dp=8)
    params = {"w": np.zeros((16, 8)), "tiny": np.zeros((3,))}
    sh = infer_sharding(params, m, zero_stage=3)
    assert sh["w"].spec == PartitionSpec("dp")
    assert sh["tiny"].spec == PartitionSpec()


def test_shard_layer_annotations():
    import paddle_tpu.nn as nn
    m = dist.init_parallel_env(tp=2)
    lin = nn.Linear(8, 4)
    lin.weight.sharding_axes = (None, "tp")
    shard_layer(lin, m)
    assert lin.weight.value.sharding.spec == PartitionSpec(None, "tp")


def test_fleet_init_and_strategy():
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=strategy)
    assert dist.fleet.mesh.shape == {"dp": 2, "pp": 2, "tp": 2}
    assert dist.fleet.worker_num() >= 1
    assert dist.fleet.is_first_worker() or dist.fleet.worker_index() > 0


def test_fleet_gradient_merge():
    import paddle_tpu.optimizer as opt
    strategy = dist.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs.k_steps = 2
    dist.fleet.init(strategy=strategy)
    sgd = opt.SGD(learning_rate=1.0)
    dopt = dist.fleet.distributed_optimizer(sgd, strategy)

    params = {"w": jnp.ones((2,))}
    state = dopt.init(params)
    g = {"w": jnp.ones((2,))}
    p1, state = dopt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(p1["w"]), [1., 1.])  # accumulated only
    p2, state = dopt.update(g, state, p1)
    np.testing.assert_allclose(np.asarray(p2["w"]), [0., 0.])  # avg grad 1 applied


def test_fleet_loss_scaler_skips_nonfinite():
    import paddle_tpu.optimizer as opt
    strategy = dist.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs.use_dynamic_loss_scaling = True
    strategy.amp_configs.init_loss_scaling = 4.0
    dist.fleet.init(strategy=strategy)
    dopt = dist.fleet.distributed_optimizer(opt.SGD(learning_rate=1.0), strategy)
    params = {"w": jnp.ones((2,))}
    state = dopt.init(params)
    bad = {"w": jnp.array([jnp.inf, 1.0])}
    p1, state = dopt.update(bad, state, params)
    np.testing.assert_allclose(np.asarray(p1["w"]), [1., 1.])  # skipped
    np.testing.assert_allclose(float(state["loss_scale"]), 2.0)  # decr_ratio
    good = {"w": jnp.array([4.0, 4.0])}
    p2, state = dopt.update(good, state, p1)
    np.testing.assert_allclose(np.asarray(p2["w"]), [-1., -1.])  # unscaled by 2


def test_fleet_lamb_swap():
    import paddle_tpu.optimizer as opt
    from paddle_tpu.optimizer.optimizers import Lamb
    strategy = dist.DistributedStrategy()
    strategy.lamb = True
    dist.fleet.init(strategy=strategy)
    dopt = dist.fleet.distributed_optimizer(opt.Adam(learning_rate=0.1), strategy)
    assert isinstance(dopt.inner, Lamb)


def test_all_reduce_subaxis_group_preserves_other_sharding():
    # Regression: reducing over one axis of a multi-axis-sharded input must
    # keep the result sharded over the untouched axes (per-dp results differ).
    from jax.sharding import NamedSharding
    m = dist.init_parallel_env(dp=2, tp=4)
    x = jax.device_put(jnp.arange(8.0), NamedSharding(m, PartitionSpec(("dp", "tp"))))
    out = dist.all_reduce(x, group="tp")
    np.testing.assert_allclose(np.asarray(out), [6.0, 22.0])
    out_spec = out.sharding.spec
    assert "dp" in str(out_spec) and "tp" not in str(out_spec)


def test_collectives_ignore_absent_group_axes():
    # Regression: a group naming an axis the mesh omitted (degree-1) must
    # reduce over the axes that exist, not crash on an unbound axis name.
    m = dist.init_parallel_env(dp=8)  # no 'tp' axis in the mesh
    out = dist.all_reduce(jnp.ones(4), group=("dp", "tp"))
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 8.0))
    out = dist.all_gather(jnp.ones((1, 2)), group=("dp", "tp"))
    assert out.shape == (8, 2)


def test_fleet_skip_step_preserves_momentum_state():
    # Regression: a non-finite (skipped) step must leave Adam moments and
    # params untouched — zeroed grads would still move params via momentum.
    import paddle_tpu.optimizer as opt
    strategy = dist.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs.use_dynamic_loss_scaling = True
    strategy.amp_configs.init_loss_scaling = 1.0
    dist.fleet.init(strategy=strategy)
    dopt = dist.fleet.distributed_optimizer(opt.Adam(learning_rate=0.1), strategy)
    params = {"w": jnp.ones((2,))}
    state = dopt.init(params)
    p1, state = dopt.update({"w": jnp.ones((2,))}, state, params)  # real step
    m_before = np.asarray(state["inner"]["per_param"][0][0])
    step_before = int(state["inner"]["step"])
    p2, state = dopt.update({"w": jnp.array([jnp.inf, 1.0])}, state, p1)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(p1["w"]))
    np.testing.assert_allclose(
        np.asarray(state["inner"]["per_param"][0][0]), m_before)
    assert int(state["inner"]["step"]) == step_before
    assert float(state["loss_scale"]) == 0.5


def test_fleet_lamb_swap_keeps_scheduler():
    import paddle_tpu.optimizer as opt
    from paddle_tpu.optimizer.lr import LRScheduler
    strategy = dist.DistributedStrategy()
    strategy.lamb = True
    dist.fleet.init(strategy=strategy)
    sched = opt.lr.StepDecay(learning_rate=0.1, step_size=10)
    dopt = dist.fleet.distributed_optimizer(
        opt.Adam(learning_rate=sched), strategy)
    assert isinstance(dopt.inner._lr, LRScheduler)


def test_distributed_optimizer_step_without_grads_raises():
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    dist.fleet.init(strategy=dist.DistributedStrategy())
    lin = nn.Linear(2, 2)
    dopt = dist.fleet.distributed_optimizer(
        opt.SGD(learning_rate=0.1, parameters=lin.parameters()))
    with pytest.raises(ValueError, match="explicit grads"):
        dopt.step()


def test_cloned_encoder_layers_keep_configured_initializer():
    import paddle_tpu.nn as nn
    layer = nn.TransformerEncoderLayer(16, 2, 32)
    enc = nn.TransformerEncoder(layer, 3)
    # every clone records an initializer on its projection weights, and
    # clone values are re-drawn (not copies of layer 0)
    w0 = None
    for i, sub in enumerate(enc.layers):
        p = sub.self_attn.q_proj.weight
        assert p.initializer is not None
        if i == 0:
            w0 = np.asarray(p.value)
        else:
            assert not np.allclose(np.asarray(p.value), w0)
