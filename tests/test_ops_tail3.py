"""Batch-3 static ops: attention_lstm, PrRoI pooling (exact integral),
tree_conv (TBCNN), filter_by_instag, pyramid_hash, var_conv_2d,
bilateral_slice (see static/ops_tail3.py for per-op reference files)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from tests.test_ops_tail2 import _run_single_op

RNG = np.random.default_rng(33)


def test_attention_lstm_shapes_and_attention_effect():
    B, T, M, D = 2, 5, 4, 3
    x = RNG.normal(0, 1, (B, T, M)).astype(np.float32)
    att_w = RNG.normal(0, 1, (M + D, 1)).astype(np.float32)
    lstm_w = RNG.normal(0, 0.3, (M + D, 4 * D)).astype(np.float32)
    lstm_b = np.zeros((4 * D,), np.float32)
    hs, cs = _run_single_op(
        "attention_lstm",
        {"X": x, "AttentionWeight": att_w, "LSTMWeight": lstm_w,
         "LSTMBias": lstm_b},
        out_slots=("Hidden", "Cell"))
    assert hs.shape == (B, T, D) and cs.shape == (B, T, D)
    assert np.isfinite(hs).all()
    # masking out later timesteps changes the pooled input -> different h
    mask = np.ones((B, T), np.float32)
    mask[:, 3:] = 0
    hs2, _ = _run_single_op(
        "attention_lstm",
        {"X": x, "Mask": mask, "AttentionWeight": att_w,
         "LSTMWeight": lstm_w, "LSTMBias": lstm_b},
        out_slots=("Hidden", "Cell"))
    assert not np.allclose(hs, hs2)


def _prroi_reference(feat, x1, y1, x2, y2, ph, pw):
    """Dense numeric integration oracle (fine sampling)."""
    S = 64
    out = np.zeros((feat.shape[0], ph, pw), np.float64)
    H, W = feat.shape[1:]

    def bilinear(c, y, x):
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        v = 0.0
        for yy, wy in ((y0, 1 - (y - y0)), (y0 + 1, y - y0)):
            for xx, wx in ((x0, 1 - (x - x0)), (x0 + 1, x - x0)):
                if 0 <= yy < H and 0 <= xx < W:
                    v += feat[c, yy, xx] * wy * wx
        return v

    bh, bw = (y2 - y1) / ph, (x2 - x1) / pw
    for c in range(feat.shape[0]):
        for i in range(ph):
            for j in range(pw):
                acc = 0.0
                for sy in range(S):
                    for sx in range(S):
                        y = y1 + (i + (sy + 0.5) / S) * bh
                        x = x1 + (j + (sx + 0.5) / S) * bw
                        acc += bilinear(c, y, x)
                out[c, i, j] = acc / (S * S)
    return out


def test_prroi_pool_matches_numeric_integral():
    feat = RNG.normal(0, 1, (1, 2, 6, 6)).astype(np.float32)
    rois = np.array([[0.7, 1.1, 4.3, 4.9]], np.float32)
    (out,) = _run_single_op(
        "prroi_pool", {"X": feat, "ROIs": rois},
        attrs={"spatial_scale": 1.0, "pooled_height": 2,
               "pooled_width": 2})
    ref = _prroi_reference(feat[0], 0.7, 1.1, 4.3, 4.9, 2, 2)
    np.testing.assert_allclose(out[0], ref, rtol=2e-3, atol=2e-3)


def test_tree_conv_matches_dfs_reference():
    """Oracle: the reference's DFS patch + eta weights, in python."""
    N, F, OUT, K, depth = 5, 3, 2, 2, 2
    x = RNG.normal(0, 1, (1, N, F)).astype(np.float32)
    # tree: 0 -> 1,2 ; 1 -> 3,4
    edges = np.full((1, 6, 2), -1, np.int64)
    edges[0, :4] = [[0, 1], [0, 2], [1, 3], [1, 4]]
    filt = RNG.normal(0, 1, (F, 3, OUT, K)).astype(np.float32)
    (out,) = _run_single_op(
        "tree_conv", {"NodesVector": x, "EdgeSet": edges, "Filter": filt},
        attrs={"max_depth": depth})

    children = {0: [1, 2], 1: [3, 4], 2: [], 3: [], 4: []}

    def eta(depth_, idx, pclen, fd=float(depth)):
        et = (fd - depth_) / fd
        temp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
        el = (1 - et) * temp
        er = (1 - et) * (1 - el)
        return et, el, er

    ref = np.zeros((N, OUT, K))
    for root in range(N):
        patch = [(root, 1, 1, 0)]
        if depth > 1:
            ch = children[root]
            for i, v in enumerate(ch):
                patch.append((v, i + 1, len(ch), 1))
        for node, idx, pclen, d in patch:
            et, el, er = eta(d, idx, pclen)
            ref[root] += (et * np.einsum("f,fok->ok", x[0, node], filt[:, 0])
                          + el * np.einsum("f,fok->ok", x[0, node],
                                           filt[:, 1])
                          + er * np.einsum("f,fok->ok", x[0, node],
                                           filt[:, 2]))
    np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-4)


def test_filter_by_instag_mask_semantics():
    x = RNG.normal(0, 1, (4, 3)).astype(np.float32)
    tags = np.array([[1, 2], [3, -1], [2, 5], [7, -1]], np.int64)
    ftags = np.array([2, 9], np.int64)
    out, w, idx = _run_single_op(
        "filter_by_instag", {"Ins": x, "Ins_tag": tags,
                             "Filter_tag": ftags},
        out_slots=("Out", "LossWeight", "IndexMap"))
    np.testing.assert_allclose(w.reshape(-1), [1, 0, 1, 0])
    np.testing.assert_allclose(out[0], x[0], rtol=1e-6)
    assert (out[1] == 0).all() and (out[3] == 0).all()


def test_pyramid_hash_ngram_embedding():
    x = np.array([[3, 5, 9, -1]], np.int64)
    w = RNG.normal(0, 1, (32, 4)).astype(np.float32)
    (out,) = _run_single_op(
        "pyramid_hash", {"X": x, "W": w},
        attrs={"space_len": 32, "pyramid_layer": 3, "num_emb": 4})
    assert out.shape == (1, 4) and np.isfinite(out).all()
    # valid n-grams: (3,5), (5,9), (3,5,9) -> sum of 3 hashed rows; the
    # padded tail contributes nothing
    x2 = np.array([[3, 5, 9, 11]], np.int64)
    (out2,) = _run_single_op(
        "pyramid_hash", {"X": x2, "W": w},
        attrs={"space_len": 32, "pyramid_layer": 3, "num_emb": 4})
    assert not np.allclose(out, out2)  # extra grams change the sum
    # deterministic
    (out3,) = _run_single_op(
        "pyramid_hash", {"X": x, "W": w},
        attrs={"space_len": 32, "pyramid_layer": 3, "num_emb": 4})
    np.testing.assert_allclose(out, out3, rtol=1e-6)


def test_var_conv_2d_masks_extents():
    x = RNG.normal(0, 1, (2, 1, 6, 6)).astype(np.float32)
    w = RNG.normal(0, 1, (2, 1, 3, 3)).astype(np.float32)
    rows = np.array([6, 3], np.int64)
    cols = np.array([6, 4], np.int64)
    (out,) = _run_single_op(
        "var_conv_2d", {"X": x, "ROW": rows, "COLUMN": cols, "W": w},
        attrs={"StrideH": 1, "StrideW": 1, "KernelH": 3, "KernelW": 3})
    assert out.shape[2:] == (6, 6)
    # sample 1's output beyond (3, 4) extent is zeroed
    assert (out[1, :, 3:, :] == 0).all() and (out[1, :, :, 4:] == 0).all()
    assert not (out[0] == 0).all()


def test_bilateral_slice_constant_grid():
    """A grid constant along depth/space must sample to that constant, and
    has_offset applies the affine coefficients."""
    N, Cin, H, W = 1, 2, 4, 4
    Cout = 2
    Cg = Cout * (Cin + 1)
    grid = np.zeros((N, Cg, 3, 2, 2), np.float32)
    co = RNG.normal(0, 1, (Cg,)).astype(np.float32)
    grid[0] = co[:, None, None, None]
    guide = RNG.uniform(0, 1, (N, H, W)).astype(np.float32)
    x = RNG.normal(0, 1, (N, Cin, H, W)).astype(np.float32)
    (out,) = _run_single_op(
        "bilateral_slice", {"X": x, "Grid": grid, "Guide": guide},
        attrs={"has_offset": True})
    comat = co.reshape(Cout, Cin + 1)
    ref = np.einsum("ci,ihw->chw", comat[:, :Cin], x[0]) + \
        comat[:, Cin][:, None, None]
    np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-4)


def test_bilateral_slice_no_offset_applies_coeffs():
    N, Cin, H, W = 1, 2, 4, 4
    Cout = 2
    Cg = Cout * Cin  # no bias column
    grid = np.zeros((N, Cg, 3, 2, 2), np.float32)
    co = RNG.normal(0, 1, (Cg,)).astype(np.float32)
    grid[0] = co[:, None, None, None]
    guide = RNG.uniform(0, 1, (N, H, W)).astype(np.float32)
    x = RNG.normal(0, 1, (N, Cin, H, W)).astype(np.float32)
    (out,) = _run_single_op(
        "bilateral_slice", {"X": x, "Grid": grid, "Guide": guide},
        attrs={"has_offset": False})
    comat = co.reshape(Cout, Cin)
    ref = np.einsum("ci,ihw->chw", comat, x[0])
    np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-4)


def test_prroi_batch_roi_nums_are_per_image_counts():
    """N == R must not confuse counts for per-ROI ids (the exact ambiguity
    the reference's per-image-counts contract forbids)."""
    feat = RNG.normal(0, 1, (2, 1, 4, 4)).astype(np.float32)
    rois = np.array([[0.0, 0.0, 3.0, 3.0], [0.0, 0.0, 3.0, 3.0]],
                    np.float32)
    counts = np.array([2, 0], np.int64)  # both rois belong to image 0
    (out,) = _run_single_op(
        "prroi_pool", {"X": feat, "ROIs": rois, "BatchRoINums": counts},
        attrs={"spatial_scale": 1.0, "pooled_height": 1,
               "pooled_width": 1})
    np.testing.assert_allclose(out[0], out[1], rtol=1e-6)  # same image


def test_sequence_reference_name_aliases():
    """The reference-NAMED sequence ops route to the padded rules."""
    x = RNG.normal(0, 1, (2, 4, 3)).astype(np.float32)
    lens = np.array([4, 2], np.int32)
    (pooled,) = _run_single_op("sequence_pool",
                               {"X": x, "Lengths": lens},
                               attrs={"pooltype": "sum"})
    mask = (np.arange(4)[None, :, None] < lens[:, None, None])
    np.testing.assert_allclose(pooled, (x * mask).sum(1), rtol=1e-5)


def test_sequence_reshape_and_scatter():
    x = RNG.normal(0, 1, (2, 4, 6)).astype(np.float32)
    (out,) = _run_single_op("sequence_reshape", {"X": x},
                            attrs={"new_dim": 8})
    assert out.shape == (2, 3, 8)
    np.testing.assert_allclose(out.reshape(2, -1), x.reshape(2, -1),
                               rtol=1e-6)
    base = np.zeros((2, 5, 3), np.float32)
    ids = np.array([[0, 2], [4, 1]], np.int64)
    upd = np.ones((2, 2, 3), np.float32)
    (sc,) = _run_single_op("sequence_scatter",
                           {"X": base, "Ids": ids, "Updates": upd})
    assert sc[0, 0].sum() == 3 and sc[0, 2].sum() == 3 and sc[0, 1].sum() == 0
    assert sc[1, 4].sum() == 3 and sc[1, 1].sum() == 3


def test_select_input_output_pair():
    a = np.full((2, 2), 1.0, np.float32)
    b = np.full((2, 2), 2.0, np.float32)
    mask = np.array([1], np.int32)
    (out,) = _run_single_op("select_input",
                            {"X": [a, b], "Mask": mask})
    np.testing.assert_allclose(out, b, rtol=1e-6)
    o0, o1 = _run_single_op("select_output",
                            {"X": a, "Mask": mask},
                            n_out={"Out": 2}, out_slots=("Out",))
    assert (o0 == 0).all() and np.allclose(o1, a)


def test_fusion_seqexpand_concat_fc():
    x = RNG.normal(0, 1, (2, 3, 4)).astype(np.float32)
    ref = RNG.normal(0, 1, (2, 5)).astype(np.float32)
    w = RNG.normal(0, 1, (9, 6)).astype(np.float32)
    (out,) = _run_single_op("fusion_seqexpand_concat_fc",
                            {"X": [x, ref], "FCWeight": w},
                            attrs={"fc_activation": "relu"})
    cat = np.concatenate([x, np.broadcast_to(ref[:, None], (2, 3, 5))], -1)
    np.testing.assert_allclose(out, np.maximum(cat @ w, 0), rtol=1e-4,
                               atol=1e-5)
