"""Native runtime layer: monitor stats, profiler, multi-slot datafeed.

Mirrors the reference's C++-side coverage of monitor/profiler/data_feed
(e.g. fluid/tests framework data_feed tests + platform profiler tests) from
Python through the ctypes bridge, plus the pure-Python fallback path.
"""
import json
import os

import numpy as np
import pytest

from paddle_tpu.core import native
from paddle_tpu.io import DatasetFactory, InMemoryDataset, QueueDataset
from paddle_tpu.io.multislot import _PySlotFeed


def _write_data(tmp_path, n_files=2, rows_per_file=25):
    files = []
    k = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        with open(p, "w") as f:
            for _ in range(rows_per_file):
                # x: 4 floats; label: 1 int — x values encode the sample id
                f.write(f"{k},{k + 0.5},{k + 0.25},{k + 0.75};{k % 10}\n")
                k += 1
        files.append(str(p))
    return files, k


SLOTS = [("x", "float32", 4), ("label", "int64", 1)]


def test_native_available():
    # g++ is baked into the image; the library must build.
    assert native.available()


def test_stats_roundtrip():
    native.stat_reset("test.counter")
    native.stat_add("test.counter", 3)
    native.stat_add("test.counter", 4)
    assert native.stat_get("test.counter") == 7
    native.stat_set("test.counter", 100)
    assert native.stat_get("test.counter") == 100
    assert native.stat_list().get("test.counter") == 100


def test_profiler_events_and_chrome_export(tmp_path):
    native.prof_clear()
    native.prof_enable()
    native.prof_push("outer")
    native.prof_push("inner")
    native.prof_pop()
    native.prof_pop()
    native.prof_add_span("external", 1000, 2000)
    native.prof_disable()
    path = str(tmp_path / "trace.json")
    n = native.prof_export_chrome(path)
    assert n == 3
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert names == {"outer", "inner", "external"}
    summary = native.prof_summary()
    assert "outer" in summary and "Calls" in summary


def test_inmemory_dataset_batches(tmp_path):
    files, total = _write_data(tmp_path)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(8)
    ds.set_filelist(files)
    assert ds.load_into_memory() == total
    assert ds.get_memory_data_size() == total

    seen = 0
    for batch in ds:
        assert set(batch) == {"x", "label"}
        assert batch["x"].dtype == np.float32 and batch["x"].shape[1] == 4
        assert batch["label"].dtype == np.int64 and batch["label"].shape[1] == 1
        # per-row consistency: label == floor(x[0]) % 10
        ids = batch["x"][:, 0].astype(np.int64)
        np.testing.assert_array_equal(batch["label"][:, 0], ids % 10)
        np.testing.assert_allclose(batch["x"][:, 1], ids + 0.5)
        seen += batch["x"].shape[0]
    assert seen == total


def test_inmemory_shuffle_is_permutation(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=40)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(40)
    ds.set_filelist(files)
    ds.load_into_memory()
    before = next(iter(ds))["x"][:, 0].copy()
    ds.local_shuffle(seed=123)
    after = next(iter(ds))["x"][:, 0].copy()
    assert sorted(before.tolist()) == sorted(after.tolist())
    assert not np.array_equal(before, after)


def test_queue_dataset_streams_and_rejects_shuffle(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=10)
    factory = DatasetFactory()
    ds = factory.create_dataset("QueueDataset")
    ds.set_use_var(SLOTS)
    ds.set_batch_size(4)
    ds.set_filelist(files)
    with pytest.raises(RuntimeError):
        ds.local_shuffle()
    rows = sum(b["x"].shape[0] for b in ds)
    assert rows == total
    # second epoch re-streams
    rows2 = sum(b["x"].shape[0] for b in ds)
    assert rows2 == total


def test_python_fallback_matches_native(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=12)
    py = _PySlotFeed(SLOTS, batch_size=5)
    py.set_filelist(files)
    assert py.load_into_memory() == total
    py_batches = list(py)

    nat = native.NativeDataFeed(SLOTS, batch_size=5)
    nat.set_filelist(files)
    nat.load_into_memory()
    nat_batches = list(nat)

    assert len(py_batches) == len(nat_batches)
    for pb, nb in zip(py_batches, nat_batches):
        np.testing.assert_allclose(pb["x"], nb["x"])
        np.testing.assert_array_equal(pb["label"], nb["label"])


def test_second_iterator_invalidates_first(tmp_path):
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=20)
    feed = native.NativeDataFeed(SLOTS, batch_size=4)
    feed.set_filelist(files)
    feed.load_into_memory()
    it1 = iter(feed)
    next(it1)
    it2 = iter(feed)  # restarts the epoch
    next(it2)
    with pytest.raises(RuntimeError, match="new epoch"):
        next(it1)


def test_setters_locked_after_build(tmp_path):
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=5)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(2)
    ds.set_filelist(files)
    ds.load_into_memory()
    with pytest.raises(RuntimeError):
        ds.set_batch_size(8)
    with pytest.raises(ValueError):
        InMemoryDataset().set_use_var([("bad;name", "float32", 1)])


def test_break_midepoch_then_release(tmp_path):
    # regression: releasing memory while the assembler thread streams must
    # not crash (worker is stopped first)
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=50)
    feed = native.NativeDataFeed(SLOTS, batch_size=2, capacity=2)
    feed.set_filelist(files)
    feed.load_into_memory()
    for _ in feed:
        break
    feed.release_memory()
    assert feed.num_samples == 0


def test_profiler_name_escaping(tmp_path):
    native.prof_clear()
    native.prof_enable()
    native.prof_push('quoted "name" \\ with\nnewline')
    native.prof_pop()
    native.prof_disable()
    path = str(tmp_path / "esc.json")
    assert native.prof_export_chrome(path) == 1
    with open(path) as f:
        trace = json.load(f)
    assert trace["traceEvents"][0]["name"] == 'quoted "name" \\ with\nnewline'


def test_short_rows_padded(tmp_path):
    p = tmp_path / "short.txt"
    # only 2 of 4 x-values present -> right-padded with zeros
    p.write_text("1.0,2.0;7\n")
    feed = native.NativeDataFeed(SLOTS, batch_size=1)
    feed.set_filelist([str(p)])
    feed.load_into_memory()
    (batch,) = list(feed)
    np.testing.assert_allclose(batch["x"][0], [1.0, 2.0, 0.0, 0.0])
    assert batch["label"][0, 0] == 7


# -- crypto (native/src/crypto.cc; ref framework/io/crypto/) -----------------

class TestCrypto:
    def test_aes256_fips197_kat(self):
        """FIPS-197 appendix C.3 single-block vector."""
        import binascii, ctypes
        from paddle_tpu.core import native
        lib = native.get_lib()
        if lib is None:
            pytest.skip("native lib unavailable")
        key = binascii.unhexlify(
            "000102030405060708090a0b0c0d0e0f"
            "101112131415161718191a1b1c1d1e1f")
        pt = binascii.unhexlify("00112233445566778899aabbccddeeff")
        out = (ctypes.c_uint8 * 16)()
        assert lib.pd_aes_encrypt_block(key, 32, pt, out) == 0
        assert bytes(out).hex() == "8ea2b7ca516745bfeafc49904b496089"

    def test_aes256_ctr_sp80038a_kat(self):
        """SP 800-38A F.5.5 CTR-AES256 first block."""
        import binascii
        from paddle_tpu.utils.crypto import Cipher
        key = binascii.unhexlify(
            "603deb1015ca71be2b73aef0857d7781"
            "1f352c073b6108d72d9810a30914dff4")
        iv = binascii.unhexlify("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
        pt = binascii.unhexlify("6bc1bee22e409f96e93d7e117393172a")
        blob = Cipher(key).encrypt(pt, iv=iv)
        # blob = magic || iv || ct || 32-byte hmac tag
        ct = blob[-32 - len(pt):-32]
        assert ct.hex() == "601ec313775789a5b7a7f504bbf3d228"

    def test_roundtrip_and_file(self, tmp_path):
        from paddle_tpu.utils.crypto import Cipher, generate_key
        key = generate_key(32)
        c = Cipher(key)
        msg = b"model bytes \x00\x01" * 1000 + b"tail"
        assert c.decrypt(c.encrypt(msg)) == msg
        p = str(tmp_path / "m.enc")
        c.encrypt_to_file(msg, p)
        assert Cipher(key).decrypt_from_file(p) == msg
        # wrong key fails authentication
        with pytest.raises(ValueError, match="authentication"):
            Cipher(generate_key(32)).decrypt_from_file(p)
        # tampered ciphertext fails authentication
        blob = bytearray(c.encrypt(msg))
        blob[30] ^= 0xFF
        with pytest.raises(ValueError, match="authentication"):
            c.decrypt(bytes(blob))
        # v1 magic (tag-stripping downgrade) is rejected, not decrypted
        v1 = b"PDTPU\x01" + c.encrypt(msg)[6:-32]
        with pytest.raises(ValueError, match="downgrade"):
            c.decrypt(v1)
        with pytest.raises(ValueError):
            Cipher(b"short")
        with pytest.raises(ValueError):
            c.decrypt(b"NOTMAGIC" + b"x" * 40)


# -- fs (paddle_tpu/utils/fs.py; ref fleet/utils/fs.py) ----------------------

class TestFS:
    def test_local_fs(self, tmp_path):
        from paddle_tpu.utils.fs import LocalFS
        fs = LocalFS()
        d = tmp_path / "a" / "b"
        fs.mkdirs(str(d))
        assert fs.is_dir(str(d))
        f = d / "x.txt"
        fs.touch(str(f))
        assert fs.is_file(str(f)) and fs.is_exist(str(f))
        dirs, files = fs.ls_dir(str(d))
        assert files == ["x.txt"]
        fs.rename(str(f), str(d / "y.txt"))
        assert fs.is_file(str(d / "y.txt"))
        fs.delete(str(d))
        assert not fs.is_exist(str(d))

    def test_hdfs_client_command_plumbing(self, tmp_path):
        """Drive HDFSClient against a stub `hadoop` executable that logs its
        argv and emulates -test/-ls, validating the full command builder +
        retry path without a cluster (the reference's design is exactly this
        CLI contract)."""
        import stat
        from paddle_tpu.utils.fs import ExecuteError, HDFSClient
        stub = tmp_path / "hadoop"
        log = tmp_path / "log"
        stub.write_text(f"""#!/bin/sh
echo "$@" >> {log}
while [ "$1" != "fs" ] && [ $# -gt 0 ]; do shift; done
shift   # drop "fs"
while [ "$1" = "-D" ]; do shift 2; done   # skip generic options
case "$1" in
  -test) [ "$3" = "hdfs:/exists" ] && exit 0 || exit 1 ;;
  -ls) echo "drwxr-xr-x - u g 0 2026-01-01 00:00 hdfs:/p/sub";
       echo "-rw-r--r-- 1 u g 9 2026-01-01 00:00 hdfs:/p/file.txt"; exit 0 ;;
  -fail) exit 1 ;;
esac
exit 0
""")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        fs = HDFSClient(hadoop_bin=str(stub), configs={"fs.default.name":
                                                       "hdfs://ns"},
                        sleep_inter=1, retries=2)
        assert fs.is_exist("hdfs:/exists")
        assert not fs.is_exist("hdfs:/missing")
        dirs, files = fs.ls_dir("hdfs:/p")
        assert dirs == ["sub"] and files == ["file.txt"]
        fs.mkdirs("hdfs:/new")
        fs.upload(__file__, "hdfs:/new/t.py")
        argv = log.read_text()
        # FsShell ordering: generic -D options AFTER the fs subcommand
        assert "fs -D fs.default.name=hdfs://ns -mkdir -p hdfs:/new" in argv
        assert "-D fs.default.name=hdfs://ns -put -f" in argv
        with pytest.raises(ExecuteError):
            fs._run("-fail", "x")

    def test_encrypted_inference_model_roundtrip(self, tmp_path):
        """save/load_inference_model with a Cipher (ref encrypted inference
        models, framework/io/crypto/)."""
        import numpy as np
        import paddle_tpu.static as static
        from paddle_tpu.static import layers as L
        from paddle_tpu.utils.crypto import Cipher, generate_key

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = L.data("x", [4])
            y = L.fc(x, 2)
        exe = static.Executor()
        exe.run(startup)
        key = generate_key()
        d = str(tmp_path / "enc_model")
        static.save_inference_model(d, ["x"], [y], exe, main_program=main,
                                    cipher=Cipher(key))
        import os
        assert os.path.exists(d + "/params.npz.enc")
        with pytest.raises(ValueError):
            static.load_inference_model(d, exe)   # encrypted, no cipher
        prog, feeds, fetches = static.load_inference_model(
            d, exe, cipher=Cipher(key))
        probe = np.random.rand(3, 4).astype("float32")
        out, = exe.run(prog, feed={"x": probe}, fetch_list=fetches)
        ref, = exe.run(main, feed={"x": probe}, fetch_list=[y])
        np.testing.assert_allclose(out, ref, rtol=1e-6)
