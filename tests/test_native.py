"""Native runtime layer: monitor stats, profiler, multi-slot datafeed.

Mirrors the reference's C++-side coverage of monitor/profiler/data_feed
(e.g. fluid/tests framework data_feed tests + platform profiler tests) from
Python through the ctypes bridge, plus the pure-Python fallback path.
"""
import json
import os

import numpy as np
import pytest

from paddle_tpu.core import native
from paddle_tpu.io import DatasetFactory, InMemoryDataset, QueueDataset
from paddle_tpu.io.multislot import _PySlotFeed


def _write_data(tmp_path, n_files=2, rows_per_file=25):
    files = []
    k = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        with open(p, "w") as f:
            for _ in range(rows_per_file):
                # x: 4 floats; label: 1 int — x values encode the sample id
                f.write(f"{k},{k + 0.5},{k + 0.25},{k + 0.75};{k % 10}\n")
                k += 1
        files.append(str(p))
    return files, k


SLOTS = [("x", "float32", 4), ("label", "int64", 1)]


def test_native_available():
    # g++ is baked into the image; the library must build.
    assert native.available()


def test_stats_roundtrip():
    native.stat_reset("test.counter")
    native.stat_add("test.counter", 3)
    native.stat_add("test.counter", 4)
    assert native.stat_get("test.counter") == 7
    native.stat_set("test.counter", 100)
    assert native.stat_get("test.counter") == 100
    assert native.stat_list().get("test.counter") == 100


def test_profiler_events_and_chrome_export(tmp_path):
    native.prof_clear()
    native.prof_enable()
    native.prof_push("outer")
    native.prof_push("inner")
    native.prof_pop()
    native.prof_pop()
    native.prof_add_span("external", 1000, 2000)
    native.prof_disable()
    path = str(tmp_path / "trace.json")
    n = native.prof_export_chrome(path)
    assert n == 3
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert names == {"outer", "inner", "external"}
    summary = native.prof_summary()
    assert "outer" in summary and "Calls" in summary


def test_inmemory_dataset_batches(tmp_path):
    files, total = _write_data(tmp_path)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(8)
    ds.set_filelist(files)
    assert ds.load_into_memory() == total
    assert ds.get_memory_data_size() == total

    seen = 0
    for batch in ds:
        assert set(batch) == {"x", "label"}
        assert batch["x"].dtype == np.float32 and batch["x"].shape[1] == 4
        assert batch["label"].dtype == np.int64 and batch["label"].shape[1] == 1
        # per-row consistency: label == floor(x[0]) % 10
        ids = batch["x"][:, 0].astype(np.int64)
        np.testing.assert_array_equal(batch["label"][:, 0], ids % 10)
        np.testing.assert_allclose(batch["x"][:, 1], ids + 0.5)
        seen += batch["x"].shape[0]
    assert seen == total


def test_inmemory_shuffle_is_permutation(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=40)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(40)
    ds.set_filelist(files)
    ds.load_into_memory()
    before = next(iter(ds))["x"][:, 0].copy()
    ds.local_shuffle(seed=123)
    after = next(iter(ds))["x"][:, 0].copy()
    assert sorted(before.tolist()) == sorted(after.tolist())
    assert not np.array_equal(before, after)


def test_queue_dataset_streams_and_rejects_shuffle(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=10)
    factory = DatasetFactory()
    ds = factory.create_dataset("QueueDataset")
    ds.set_use_var(SLOTS)
    ds.set_batch_size(4)
    ds.set_filelist(files)
    with pytest.raises(RuntimeError):
        ds.local_shuffle()
    rows = sum(b["x"].shape[0] for b in ds)
    assert rows == total
    # second epoch re-streams
    rows2 = sum(b["x"].shape[0] for b in ds)
    assert rows2 == total


def test_python_fallback_matches_native(tmp_path):
    files, total = _write_data(tmp_path, n_files=1, rows_per_file=12)
    py = _PySlotFeed(SLOTS, batch_size=5)
    py.set_filelist(files)
    assert py.load_into_memory() == total
    py_batches = list(py)

    nat = native.NativeDataFeed(SLOTS, batch_size=5)
    nat.set_filelist(files)
    nat.load_into_memory()
    nat_batches = list(nat)

    assert len(py_batches) == len(nat_batches)
    for pb, nb in zip(py_batches, nat_batches):
        np.testing.assert_allclose(pb["x"], nb["x"])
        np.testing.assert_array_equal(pb["label"], nb["label"])


def test_second_iterator_invalidates_first(tmp_path):
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=20)
    feed = native.NativeDataFeed(SLOTS, batch_size=4)
    feed.set_filelist(files)
    feed.load_into_memory()
    it1 = iter(feed)
    next(it1)
    it2 = iter(feed)  # restarts the epoch
    next(it2)
    with pytest.raises(RuntimeError, match="new epoch"):
        next(it1)


def test_setters_locked_after_build(tmp_path):
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=5)
    ds = InMemoryDataset()
    ds.set_use_var(SLOTS)
    ds.set_batch_size(2)
    ds.set_filelist(files)
    ds.load_into_memory()
    with pytest.raises(RuntimeError):
        ds.set_batch_size(8)
    with pytest.raises(ValueError):
        InMemoryDataset().set_use_var([("bad;name", "float32", 1)])


def test_break_midepoch_then_release(tmp_path):
    # regression: releasing memory while the assembler thread streams must
    # not crash (worker is stopped first)
    files, _ = _write_data(tmp_path, n_files=1, rows_per_file=50)
    feed = native.NativeDataFeed(SLOTS, batch_size=2, capacity=2)
    feed.set_filelist(files)
    feed.load_into_memory()
    for _ in feed:
        break
    feed.release_memory()
    assert feed.num_samples == 0


def test_profiler_name_escaping(tmp_path):
    native.prof_clear()
    native.prof_enable()
    native.prof_push('quoted "name" \\ with\nnewline')
    native.prof_pop()
    native.prof_disable()
    path = str(tmp_path / "esc.json")
    assert native.prof_export_chrome(path) == 1
    with open(path) as f:
        trace = json.load(f)
    assert trace["traceEvents"][0]["name"] == 'quoted "name" \\ with\nnewline'


def test_short_rows_padded(tmp_path):
    p = tmp_path / "short.txt"
    # only 2 of 4 x-values present -> right-padded with zeros
    p.write_text("1.0,2.0;7\n")
    feed = native.NativeDataFeed(SLOTS, batch_size=1)
    feed.set_filelist([str(p)])
    feed.load_into_memory()
    (batch,) = list(feed)
    np.testing.assert_allclose(batch["x"][0], [1.0, 2.0, 0.0, 0.0])
    assert batch["label"][0, 0] == 7
