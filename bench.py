"""Flagship benchmark: ERNIE-base MLM+NSP pretraining throughput (tok/s/chip).

BASELINE.json config 3 ("PaddleNLP ERNIE-1.0 / BERT-base pretrain") on the
available chip(s).  No published reference numbers exist (BASELINE.md:
`"published": {}`), so vs_baseline is reported against the first number this
harness recorded; until then it is 1.0 (this run *is* the baseline).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""
from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from __graft_entry__ import make_train_step
from paddle_tpu.autograd import parameters_dict
from paddle_tpu.optimizer import Adam
from paddle_tpu.text.ernie import (
    ErnieConfig,
    ErnieForPretraining,
    ErniePretrainingCriterion,
)

# The first recorded TPU measurement is the baseline (BASELINE.md):
# round 1 measured 44,322 tok/s/chip on this config (BENCH_r01.json).
# vs_baseline therefore reports progress against r01; override with
# BENCH_BASELINE_TOKS to rebase.
BASELINE_TOK_PER_SEC = float(os.environ.get("BENCH_BASELINE_TOKS", "")
                             or 44322.17)


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # Full ERNIE-base on an accelerator; scaled-down config on CPU so local
    # smoke runs finish (the driver records TPU numbers only).
    recompute = os.environ.get("BENCH_RECOMPUTE", "0") == "1"
    if on_tpu:
        cfg = ErnieConfig(enable_recompute=recompute)  # L12 H768 A12 V18000
        batch, seq = int(os.environ.get("BENCH_BATCH", "64")), 512
        warmup, iters = 3, int(os.environ.get("BENCH_ITERS", "40"))
    else:
        cfg = ErnieConfig(vocab_size=1024, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=256, max_position_embeddings=128)
        batch, seq = 8, 128
        warmup, iters = 1, 3

    model = ErnieForPretraining(cfg)
    model.train()
    criterion = ErniePretrainingCriterion(cfg.vocab_size)
    opt = Adam(learning_rate=1e-4)

    params = parameters_dict(model)
    opt_state = opt.init(params)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    step = jax.jit(make_train_step(model, criterion, opt, compute_dtype),
                   donate_argnums=(0, 1))

    rng = np.random.default_rng(0)
    # ERNIE pretraining contract (ref PaddleNLP ernie pretraining reader):
    # feed mask_pos so only masked tokens hit the vocab projection.
    n_mask = max(1, int(seq * 0.15))
    mask_pos = np.stack([rng.choice(seq, n_mask, replace=False)
                         for _ in range(batch)]).astype(np.int32)
    batch_data = {
        "input_ids": jnp.asarray(
            rng.integers(1, cfg.vocab_size, (batch, seq)), jnp.int32),
        "token_type_ids": jnp.zeros((batch, seq), jnp.int32),
        "masked_positions": jnp.asarray(mask_pos),
        "mlm_labels": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, n_mask)), jnp.int32),
        "nsp_labels": jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32),
    }
    # rbg (hardware) PRNG for dropout: threefry mask generation alone costs
    # ~45ms/step at this shape (measured r03); the typed key carries its
    # impl into every fold_in/bernoulli downstream.
    key = jax.random.key(0, impl="rbg" if on_tpu else "threefry2x32")

    # Sync via a host read of the (scalar) loss every k steps: on the axon
    # TPU tunnel, block_until_ready does not reliably wait and deep
    # unsynchronized dispatch chains wedge the device.  Steps already chain
    # through donated params, so a sync every k steps bounds the outstanding
    # dispatch depth while amortizing the tunnel round-trip — measured
    # ~120 ms dead time per sync (r03), i.e. 30 ms/step at k=4 vs 6 ms/step
    # at k=20.  k=20 has run clean repeatedly; tighten via env if the
    # tunnel regresses.
    sync_every = int(os.environ.get("BENCH_SYNC_EVERY", "40"))
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch_data, key)
        float(loss)

    t0 = time.perf_counter()
    for i in range(iters):
        params, opt_state, loss = step(params, opt_state, batch_data, key)
        if (i + 1) % sync_every == 0 or i == iters - 1:
            float(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.local_device_count() if on_tpu else 1
    toks_per_sec = batch * seq * iters / dt / n_chips

    # Analytic model FLOPs per token (training = 3x forward matmul FLOPs):
    # per layer QKV+out projections 8H^2, FFN 4HI, attention scores+values
    # 4sH; MLM head only touches the masked fraction of tokens; pooler+NSP
    # amortize per sequence.  (6*n_params would overcount the embedding
    # gather and the unmasked tokens' vocab projection.)
    H, I, L, V = (cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_hidden_layers, cfg.vocab_size)
    mask_frac = n_mask / seq
    fwd_per_tok = (L * (8 * H * H + 4 * H * I + 4 * seq * H)
                   + mask_frac * (2 * H * H + 2 * H * V)
                   + (2 * H * H + 4 * H) / seq)
    flops_per_tok = 3 * fwd_per_tok
    peak = {"tpu": 197e12}.get(platform, 1e12)  # v5e bf16 peak per chip
    mfu = toks_per_sec * flops_per_tok / peak

    vs = toks_per_sec / BASELINE_TOK_PER_SEC if BASELINE_TOK_PER_SEC else 1.0
    print(json.dumps({
        "metric": "ernie_base_pretrain_throughput",
        "value": round(toks_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
        "platform": platform,
        "batch": batch, "seq_len": seq, "iters": iters,
        "loss": round(float(loss), 4),
        "mfu_est": round(mfu, 4) if on_tpu else None,
    }))


if __name__ == "__main__":
    main()
