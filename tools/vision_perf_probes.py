"""Vision perf probes behind the r05 ResNet-50 ladder (BASELINE.md).

Consolidates the round-5 profiling scripts into one reproducible harness.
Probes (select by name on the command line; default runs all):

  matmul     8192^3 bf16 matmul in a fori_loop — the chip/harness sanity
             ceiling (reads ~77% MFU through the axon tunnel)
  floor      tiny-op fori_loop — the per-iteration fixed overhead
  convs      marginal per-conv cost via 1/2/4 chained convs (the ONLY
             valid per-op timing over this tunnel; single-op loops are
             floor-dominated, host-chained calls pay a ~40-80 ms RTT each)
  steps      ResNet-50 train step: K jit calls vs ONE jit with
             lax.fori_loop over K steps (dispatch pipelining check)
  fwdbwd     fwd-only and fwd+bwd device time inside fori_loop
  batch      full-step time at batch 256 vs 512 (overhead-bound check)

Every probe chains iterations through `x + (mean(y)*1e-12).astype(dtype)`
— a structural dependence XLA cannot hoist that is numerically a bf16
no-op.  See BASELINE.md "r05 ResNet-50 ladder" for the recorded numbers
and conclusions.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

PEAK = 197e12


def _time_loop(body, x0, iters):
    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, iters, body, x)

    jax.block_until_ready(run(x0))
    t0 = time.perf_counter()
    jax.block_until_ready(run(x0))
    return (time.perf_counter() - t0) / iters


def _chain(x, y):
    return x + (jnp.mean(y) * 1e-12).astype(x.dtype)


def probe_matmul():
    n = 8192
    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)) * 0.01,
                    jnp.bfloat16)

    def body(i, x):
        y = x @ a
        return y / (jnp.max(jnp.abs(y)).astype(y.dtype) + 1.0)

    dt = _time_loop(body, a, 100)
    print(json.dumps({"probe": "matmul8192", "ms": round(dt * 1e3, 2),
                      "mfu": round(2 * n ** 3 / dt / PEAK, 3)}))


def probe_floor():
    rng = np.random.default_rng(3)
    a0 = jnp.asarray(rng.standard_normal((128, 128)), jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((128, 128)) * 0.05, jnp.bfloat16)
    dt = _time_loop(lambda i, a: _chain(a, a @ b), a0, 50)
    print(json.dumps({"probe": "tiny_matmul128_floor",
                      "ms_per_iter": round(dt * 1e3, 3)}))


def probe_convs():
    rng = np.random.default_rng(4)
    x0 = jnp.asarray(rng.standard_normal((256, 14, 14, 256)), jnp.bfloat16)
    ws = [jnp.asarray(rng.standard_normal((256, 256, 3, 3)) * 0.05,
                      jnp.bfloat16) for _ in range(4)]

    def mk(k):
        def body(i, x):
            y = x
            for w in ws[:k]:
                y = jnp.tanh(jax.lax.conv_general_dilated(
                    y, w, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "OIHW", "NHWC")))
            return _chain(x, y)
        return body

    times = {k: _time_loop(mk(k), x0, 50) for k in (1, 2, 4)}
    for k, dt in times.items():
        print(json.dumps({"probe": f"conv_l3_x{k}",
                          "ms": round(dt * 1e3, 3)}))
    marginal = (times[4] - times[1]) / 3
    flops = 2 * 256 * 14 * 14 * 256 * 256 * 9
    print(json.dumps({"probe": "conv_l3_marginal",
                      "ms": round(marginal * 1e3, 3),
                      "mfu": round(flops / marginal / PEAK, 3)}))


def _resnet_setup(batch):
    from paddle_tpu import autograd
    from paddle_tpu.autograd import parameters_dict
    from paddle_tpu.optimizer import Momentum
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision import models as M

    model = M.resnet50(num_classes=1000)
    model.train()
    opt = Momentum(learning_rate=0.1, momentum=0.9)
    params = parameters_dict(model)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((batch, 3, 224, 224)),
                         jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (batch, 1)), jnp.int32)

    def cast(p):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

    def loss_of(p_, imgs):
        logits = autograd.functional_call(model, cast(p_), (imgs,))
        return jnp.mean(F.cross_entropy(logits.astype(jnp.float32), labels))

    def one_step(p, s):
        loss, grads = jax.value_and_grad(loss_of)(p, images)
        p, s = opt.update(grads, s, p)
        return p, s, loss

    return params, opt_state, images, loss_of, one_step


def probe_steps():
    K = 10
    params, opt_state, images, loss_of, one_step = _resnet_setup(256)
    step = jax.jit(one_step)
    p, s, loss = step(params, opt_state)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(K):
        p, s, loss = step(p, s)
    float(loss)
    dt_calls = (time.perf_counter() - t0) / K

    @jax.jit
    def k_steps(p, s):
        def body(i, carry):
            p, s, _ = carry
            return one_step(p, s)
        return jax.lax.fori_loop(0, K, body,
                                 (p, s, jnp.zeros((), jnp.float32)))

    out = k_steps(params, opt_state)
    float(out[2])
    t0 = time.perf_counter()
    out = k_steps(params, opt_state)
    float(out[2])
    dt_fori = (time.perf_counter() - t0) / K
    for name, dt in [("step_calls", dt_calls), ("step_foriloop", dt_fori)]:
        print(json.dumps({"probe": f"resnet50_{name}",
                          "ms": round(dt * 1e3, 2),
                          "mfu": round(3 * 4.09e9 * 256 / dt / PEAK, 4)}))


def probe_fwdbwd():
    K = 10
    params, _, images, loss_of, _ = _resnet_setup(256)

    @jax.jit
    def fwd_loop(imgs):
        def body(i, im):
            return im + (loss_of(params, im) * 1e-12).astype(im.dtype)
        return jax.lax.fori_loop(0, K, body, imgs)

    jax.block_until_ready(fwd_loop(images))
    t0 = time.perf_counter()
    jax.block_until_ready(fwd_loop(images))
    dt = (time.perf_counter() - t0) / K
    print(json.dumps({"probe": "resnet50_fwd_loop", "ms":
                      round(dt * 1e3, 2),
                      "mfu": round(4.09e9 * 256 / dt / PEAK, 4)}))

    @jax.jit
    def fwdbwd_loop(imgs):
        def body(i, im):
            loss, grads = jax.value_and_grad(loss_of)(params, im)
            g0 = jax.tree_util.tree_leaves(grads)[0]
            return im + (loss * 1e-12).astype(im.dtype) \
                + (jnp.mean(g0) * 1e-12).astype(im.dtype)
        return jax.lax.fori_loop(0, K, body, imgs)

    jax.block_until_ready(fwdbwd_loop(images))
    t0 = time.perf_counter()
    jax.block_until_ready(fwdbwd_loop(images))
    dt = (time.perf_counter() - t0) / K
    print(json.dumps({"probe": "resnet50_fwdbwd_loop",
                      "ms": round(dt * 1e3, 2),
                      "mfu": round(3 * 4.09e9 * 256 / dt / PEAK, 4)}))


def probe_batch():
    for batch in (256, 512):
        params, opt_state, _, _, one_step = _resnet_setup(batch)
        step = jax.jit(one_step)
        p, s, loss = step(params, opt_state)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(10):
            p, s, loss = step(p, s)
        float(loss)
        dt = (time.perf_counter() - t0) / 10
        print(json.dumps({"probe": f"resnet50_bs{batch}",
                          "ms": round(dt * 1e3, 2),
                          "ips": round(batch / dt, 1),
                          "mfu": round(3 * 4.09e9 * batch / dt / PEAK,
                                       4)}))


PROBES = {"matmul": probe_matmul, "floor": probe_floor,
          "convs": probe_convs, "steps": probe_steps,
          "fwdbwd": probe_fwdbwd, "batch": probe_batch}

if __name__ == "__main__":
    for name in (sys.argv[1:] or list(PROBES)):
        PROBES[name]()
