"""autoplan — CLI for the cost-model-driven sharding-plan search.

Front-end for ``paddle_tpu.parallel.autoplan``: builds one of the
built-in demo models (no stable serialized Program format yet), searches
the plan space over an emulated N-device CPU mesh, and prints the ranked
candidate table — predicted comm bytes / peak HBM / roofline ms, the
ledger-corrected score, and (with ``--measure-top K``) a measured
step-time column from actually executing the leading candidates, so the
cost model's ranking can be eyeballed against reality.

Demo models (``--model``):

  * ``fc``       — the shardcheck demo tower (hand plan: pure dp)
  * ``toylm``    — ERNIE-toy: embedding + 2-layer MLP head (hand plan:
                   dp2 x tp4, Megatron column/row annotations, vocab-
                   sharded embedding)
  * ``resblock`` — a ResNet block: conv-bn-relu x2 + skip (hand plan:
                   pure dp; conv weights are 4-D so dp is the space)
  * ``rec``      — recbench's wide&deep CTR model (hand plan: tp8
                   vocab-sharded embeddings, recbench's own)

Usage::

    python -m tools.autoplan [--model fc] [--devices 8] [--top 12]
    python -m tools.autoplan --format json
    python -m tools.autoplan --measure-top 3 --steps 8
    python -m tools.autoplan --selfcheck     # CI probe; rides tier-1

``--selfcheck`` asserts, per demo: (1) the search's best predicted score
reproduces or beats the hand-written plan's score under the same cost
model; (2) every candidate was priced WITHOUT compiling anything
(``executor.traces`` flat across the search — SC/MC-invalid candidates
provably never trace); (3) executing the chosen plan next to the hand
plan from identical init yields matching loss curves and a measured
step time within tolerance-or-better; (4) steady state under the chosen
plan never retraces.  Exits non-zero on any violation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _ensure_cpu_devices(n: int) -> None:
    """Must run BEFORE jax imports: force enough virtual XLA host devices
    for an N-way mesh (no-op when a harness already exported XLA_FLAGS)."""
    if "jax" in sys.modules:
        return
    env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in env:
        os.environ["XLA_FLAGS"] = (
            env + f" --xla_force_host_platform_device_count={n}").strip()


# ---------------------------------------------------------------------------
# Demo models: (main, startup, loss, feed dict, hand-written plan builder)
# ---------------------------------------------------------------------------

def _build_fc(batch: int):
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [64])
        y = L.data("y", [1])
        h = L.fc(x, 128, act="relu")
        h = L.fc(h, 128, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)
    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(size=(batch, 64)).astype(np.float32),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}

    def hand_plan(devices):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.sharding import ShardingPlan

        return ShardingPlan(mesh=Mesh(np.asarray(devices), ("dp",)))

    return main, startup, loss, feed, hand_plan


def _build_toylm(batch: int, vocab: int = 512, dim: int = 64, seq: int = 16):
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = L.data("ids", [seq], dtype="int64")
        y = L.data("y", [1])
        emb = L.embedding(ids, size=[vocab, dim], name="tok_emb")
        h = L.reshape(emb, (-1, seq * dim))
        h = L.fc(h, 4 * dim, act="relu")     # "ffn in"  -> column-parallel
        h = L.fc(h, dim, act="relu")         # "ffn out" -> row-parallel
        pred = L.fc(h, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)
    rng = np.random.default_rng(0)
    feed = {"ids": rng.integers(0, vocab, size=(batch, seq)).astype(np.int64),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}

    def hand_plan(devices):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.sharding import ShardingPlan

        mesh = Mesh(np.asarray(devices).reshape(2, len(devices) // 2),
                    ("dp", "tp"))
        tp = int(mesh.shape["tp"])
        # the Megatron layout by hand: ffn-in column-parallel, ffn-out
        # row-parallel (picked by shape), vocab-sharded embedding
        ann = {}
        col = True
        for p in main.all_parameters():
            shape = tuple(p.shape)
            if len(shape) != 2 or p.name == "tok_emb.w":
                continue
            if col and shape[1] % tp == 0:
                ann[p.name] = (None, "tp")
                col = False
            elif not col and shape[0] % tp == 0:
                ann[p.name] = ("tp", None)
                col = True
        return ShardingPlan(mesh=mesh, annotations=ann,
                            embedding_shard="tp")

    return main, startup, loss, feed, hand_plan


def _build_resblock(batch: int, channels: int = 8, hw: int = 8):
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [channels, hw, hw])
        y = L.data("y", [1])
        h = L.conv2d(x, channels, 3, padding=1, act="relu")
        h = L.conv2d(h, channels, 3, padding=1)
        h = L.relu(L.elementwise_add(h, x))          # the skip
        flat = L.reshape(h, (-1, channels * hw * hw))
        pred = L.fc(flat, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)
    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(size=(batch, channels, hw, hw)).astype(np.float32),
            "y": rng.normal(size=(batch, 1)).astype(np.float32)}

    def hand_plan(devices):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.sharding import ShardingPlan

        return ShardingPlan(mesh=Mesh(np.asarray(devices), ("dp",)))

    return main, startup, loss, feed, hand_plan


def _build_rec(batch: int, vocab: int = 256, dim: int = 8, slots: int = 4):
    import numpy as np
    from tools.recbench import _build_ctr, _zipf_ids

    main, startup, loss, _emb_out, _wname = _build_ctr(vocab, dim, slots,
                                                       lr=0.05)
    rng = np.random.default_rng(0)
    feed = {"ids": _zipf_ids(rng, vocab, (batch, slots)),
            "y": (rng.random(size=(batch, 1)) < 0.3).astype(np.float32)}

    def hand_plan(devices):
        from jax.sharding import Mesh
        from paddle_tpu.parallel.sharding import ShardingPlan

        # recbench's own: every device on tp, blanket vocab sharding
        mesh = Mesh(np.asarray(devices).reshape(1, len(devices)),
                    ("dp", "tp"))
        return ShardingPlan(mesh=mesh, embedding_shard="tp")

    return main, startup, loss, feed, hand_plan


_DEMOS = {"fc": _build_fc, "toylm": _build_toylm,
          "resblock": _build_resblock, "rec": _build_rec}


# ---------------------------------------------------------------------------
# Execution: measure a plan for real
# ---------------------------------------------------------------------------

def _measure_plan(main, startup, loss, feed, plan, steps: int,
                  init=None):
    """(losses, ms_per_step, retraces, init) executing ``plan`` for
    ``steps`` steps — warmup (compile) excluded from the timing, retraces
    counted across the timed loop.  ``init`` seeds identical parameters
    across measured plans (captured on first call)."""
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.utils import monitor

    exe = static.Executor()
    scope = static.Scope()
    traces = monitor.default_registry().counter("executor.traces")
    with static.scope_guard(scope):
        exe.run(startup)
        if init is None:
            init = {p.name: np.array(scope.find_var(p.name))
                    for p in main.all_parameters()}
        else:
            for p in main.all_parameters():
                if p.name in init:
                    scope.set(p.name, init[p.name])
        compiled = static.CompiledProgram(main).with_sharding(plan=plan)
        losses = [float(np.asarray(
            exe.run(compiled, feed=feed, fetch_list=[loss])[0]).item())]
        warm = traces.value()
        t0 = time.perf_counter()
        for _ in range(max(1, steps - 1)):
            losses.append(float(np.asarray(
                exe.run(compiled, feed=feed, fetch_list=[loss])[0]).item()))
        dt = time.perf_counter() - t0
        retraces = traces.value() - warm
    return losses, dt * 1e3 / max(1, steps - 1), int(retraces), init


def _run_model(name: str, devices_n: int, batch: int):
    """(choice, hand_candidate, parts) — the search + the hand plan scored
    under the same corrections."""
    import jax
    from paddle_tpu.parallel import autoplan
    from paddle_tpu.static import memcheck as _memcheck

    build = _DEMOS[name]
    main, startup, loss, feed, hand_plan = build(batch)
    devices = list(jax.devices()[:devices_n])
    feed_shapes = _memcheck._feed_shape_dict(feed)
    choice = autoplan.search(main, devices=devices,
                             feed_shapes=feed_shapes,
                             fetch_names=(loss.name,))
    hand = autoplan.score_plan(main, hand_plan(devices),
                               feed_shapes=feed_shapes,
                               fetch_names=(loss.name,),
                               corrections=choice.corrections)
    hand.desc["placement"] = "hand"
    return choice, hand, (main, startup, loss, feed)


def _measure_top(choice, hand, parts, k: int, steps: int) -> None:
    """Execute the top-K candidates + the hand plan; fill measured
    columns in place."""
    main, startup, loss, feed = parts
    init = None
    for cand in [hand] + choice.ranked[:k]:
        losses, ms, retraces, init = _measure_plan(
            main, startup, loss, feed, cand.plan, steps, init)
        cand.measured = {"step_time_ms": ms, "final_loss": losses[-1],
                         "retraces": retraces}


# ---------------------------------------------------------------------------
# selfcheck: rides tier-1
# ---------------------------------------------------------------------------

def selfcheck(devices_n: int = 8, steps: int = 6) -> int:
    from paddle_tpu.utils import monitor

    traces = monitor.default_registry().counter("executor.traces")
    failures = []
    for name in ("fc", "toylm", "resblock", "rec"):
        t0 = traces.value()
        choice, hand, parts = _run_model(name, devices_n, batch=16)
        if traces.value() != t0:
            failures.append(f"{name}: the search itself compiled/traced "
                            "(pruning must be static)")
            continue
        if not choice.ranked:
            failures.append(f"{name}: no surviving candidates")
            continue
        best = choice.ranked[0]
        if hand.score is not None and best.score > hand.score * 1.001:
            failures.append(
                f"{name}: best predicted score {best.score:.4f}ms loses to "
                f"hand-written {hand.score:.4f}ms ({hand.plan.fingerprint()})")
            continue
        # execution parity: chosen vs hand from identical init
        main, startup, loss, feed = parts
        h_losses, h_ms, _h_re, init = _measure_plan(
            main, startup, loss, feed, hand.plan, steps)
        b_losses, b_ms, b_re, _ = _measure_plan(
            main, startup, loss, feed, best.plan, steps, init)
        import numpy as np

        if not np.allclose(h_losses, b_losses, rtol=5e-3, atol=1e-6):
            failures.append(f"{name}: loss curves diverge between chosen "
                            f"and hand plan: {b_losses} vs {h_losses}")
        if b_re != 0:
            failures.append(f"{name}: chosen plan retraced {b_re}x in "
                            "steady state")
        # CPU dispatch wall time is noisy — the gate is coarse
        # tolerance-or-better, not a benchmark
        if b_ms > h_ms * 3.0 + 5.0:
            failures.append(f"{name}: chosen plan measured {b_ms:.2f}ms/step"
                            f" vs hand {h_ms:.2f}ms/step (beyond tolerance)")
        print(f"  {name}: best={best.label!r} score={best.score:.4f}ms "
              f"hand={hand.score:.4f}ms measured {b_ms:.2f} vs "
              f"{h_ms:.2f} ms/step "
              f"({len(choice.ranked)} ok / {len(choice.pruned)} pruned)")
    if failures:
        for f in failures:
            print(f"autoplan selfcheck: {f}", file=sys.stderr)
        return 1
    print("autoplan selfcheck: OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.autoplan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--model", choices=sorted(_DEMOS), default="fc")
    parser.add_argument("--devices", type=int, default=8,
                        help="emulated CPU mesh size (default 8)")
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--top", type=int, default=12,
                        help="table rows to print (default 12)")
    parser.add_argument("--measure-top", type=int, default=0, metavar="K",
                        help="execute the top K candidates (+ the hand "
                        "plan) and add measured columns")
    parser.add_argument("--steps", type=int, default=6,
                        help="steps per measured plan (with --measure-top)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--selfcheck", action="store_true",
                        help="CI probe: reproduce-or-beat the hand-written "
                        "plans, static pruning, execution parity")
    args = parser.parse_args(argv)

    _ensure_cpu_devices(args.devices)

    if args.selfcheck:
        return selfcheck(args.devices)

    choice, hand, parts = _run_model(args.model, args.devices, args.batch)
    if args.measure_top > 0:
        _measure_top(choice, hand, parts, args.measure_top, args.steps)
    if args.format == "json":
        doc = choice.to_dict()
        doc["hand"] = hand.to_dict()
        print(json.dumps(doc, sort_keys=True))
    else:
        print(choice.render(top=args.top))
        hs = f"{hand.score:.3f}" if hand.score is not None else "-"
        hm = (f"  measured {hand.measured['step_time_ms']:.3f}ms/step"
              if "step_time_ms" in hand.measured else "")
        print(f"hand-written plan [{hand.label}]: score {hs}ms{hm}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
