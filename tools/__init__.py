# repo tooling package (enables `python -m tools.proglint`)
