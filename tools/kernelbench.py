"""Micro-benchmark of the Pallas vision kernels (ops/pallas/).

Runs each kernel directly (not through the static-graph dispatch gates) on
one representative shape, checks it against the plain-XLA reference, and
prints exactly ONE JSON line::

    {"backend": "cpu", "interpret": true, "iters": 5, "kernels": [
      {"kernel": "conv2d_bn_act", "shape": "...", "ms": ..,
       "flops": .., "bytes": .., "gflops_s": .., "gb_s": ..,
       "intensity": .., "max_abs_err": .., "tol": ..}, ...]}

* ``flops``/``bytes`` come from the SAME cost models the kernels register
  with ops/pallas/config.register_cost — so xprof attribution, roofline
  analysis and this tool can never disagree about what a call "should"
  cost.  ``intensity`` is flops/byte (compare against the TPU ridge).
* Off-TPU the kernels run in Pallas interpret mode: wall times then
  measure the interpreter, not the hardware — the modeled numbers are the
  portable output, the measured ones are only meaningful on a real TPU.
* ``max_abs_err`` is the deviation from the unfused XLA reference; every
  row carries its ``tol`` and the tool exits non-zero when any row is out
  of bound, so the bench doubles as a numerics canary.

Usage:
    python -m tools.kernelbench [--iters K] [--batch N] [--hw H] [--ch C]
    python -m tools.kernelbench --selfcheck     # tiny shapes: rides tier-1
"""
from __future__ import annotations

import argparse
import functools
import json
import statistics
import sys
import time


def _bench(fn, iters: int):
    """(result, median wall ms) — first call outside the clock (compile)."""
    import jax

    out = jax.block_until_ready(fn())
    times = []
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append((time.perf_counter() - t0) * 1e3)
    return out, statistics.median(times)


def _row(name, shape, ms, flops, bytes_, err, tol):
    return {
        "kernel": name,
        "shape": shape,
        "ms": round(ms, 4),
        "flops": float(flops),
        "bytes": float(bytes_),
        "gflops_s": round(flops / (ms * 1e6), 3) if ms > 0 else 0.0,
        "gb_s": round(bytes_ / (ms * 1e6), 3) if ms > 0 else 0.0,
        "intensity": round(flops / bytes_, 3) if bytes_ else 0.0,
        "max_abs_err": float(err),
        "tol": float(tol),
    }


def run_bench(iters: int, n: int, hw: int, ch: int, mk: int):
    """All kernel rows for one (batch, spatial, channel, matmul-dim) size."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas import conv_fused as _cf
    from paddle_tpu.ops.pallas import int8 as _int8
    from paddle_tpu.ops.pallas import pooling as _pool

    rng = np.random.default_rng(0)
    rows = []
    dn = ("NHWC", "OIHW", "NHWC")

    # -- fused conv + BN + act (inference epilogue) ---------------------------
    kh = kw = 3
    x = rng.normal(size=(n, hw, hw, ch)).astype(np.float32)
    w = (rng.normal(size=(ch, ch, kh, kw)) * 0.1).astype(np.float32)
    a = rng.uniform(0.5, 1.5, size=(ch,)).astype(np.float32)
    b = rng.normal(size=(ch,)).astype(np.float32)
    fused = jax.jit(functools.partial(
        _cf.conv2d_bn_act, stride=(1, 1), padding=(1, 1), act="relu"))
    got, ms = _bench(lambda: fused(x, w, a, b), iters)
    ref = jax.nn.relu(jax.lax.conv_general_dilated(
        x, jnp.transpose(w, (2, 3, 1, 0)), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC")) * a + b)
    flops, bytes_ = _cf.conv_cost(n, hw, hw, ch, ch, kh, kw,
                                  in_h=hw, in_w=hw)
    rows.append(_row("conv2d_bn_act", f"{n}x{hw}x{hw}x{ch} k{kh}", ms,
                     flops, bytes_, jnp.abs(got - ref).max(), 1e-3))

    # -- fused BN-stats + scale/shift + act (training mode) -------------------
    gamma = rng.uniform(0.5, 1.5, size=(ch,)).astype(np.float32)
    beta = rng.normal(size=(ch,)).astype(np.float32)
    bn = jax.jit(functools.partial(_cf.fused_bn_act_train, eps=1e-5,
                                   act="relu"))
    (y, mean, var), ms = _bench(lambda: bn(x, gamma, beta), iters)
    x2 = x.reshape(-1, ch)
    rmean = x2.mean(0)
    rvar = x2.var(0)
    ref = np.maximum((x2 - rmean) / np.sqrt(rvar + 1e-5) * gamma + beta, 0.0)
    err = max(float(jnp.abs(y.reshape(-1, ch) - ref).max()),
              float(jnp.abs(mean - rmean).max()),
              float(jnp.abs(var - rvar).max()))
    flops, bytes_ = _cf.bn_act_cost(n * hw * hw, ch)
    rows.append(_row("bn_act_train", f"{n}x{hw}x{hw}x{ch}", ms,
                     flops, bytes_, err, 1e-3))

    # -- NHWC pooling ---------------------------------------------------------
    for mode, fn, init, red in (
            ("max_pool2d", _pool.max_pool2d_nhwc, -np.inf, jax.lax.max),
            ("avg_pool2d", _pool.avg_pool2d_nhwc, 0.0, jax.lax.add)):
        pooled = jax.jit(functools.partial(fn, kernel=(2, 2), stride=(2, 2),
                                           padding=(0, 0)))
        got, ms = _bench(lambda: pooled(x), iters)
        ref = jax.lax.reduce_window(x, init, red, (1, 2, 2, 1),
                                    (1, 2, 2, 1), "VALID")
        if mode == "avg_pool2d":
            ref = ref / 4.0
        oh = hw // 2
        flops, bytes_ = _pool.pool_cost(n, oh, oh, ch, 2, 2, in_h=hw,
                                        in_w=hw)
        rows.append(_row(mode, f"{n}x{hw}x{hw}x{ch} k2s2", ms, flops,
                         bytes_, jnp.abs(got - ref).max(), 1e-5))

    # -- int8 matmul with fp32 per-channel dequant epilogue -------------------
    xq = rng.integers(-127, 128, size=(mk, mk), dtype=np.int8)
    wq = rng.integers(-127, 128, size=(mk, mk), dtype=np.int8)
    scale = rng.uniform(1e-4, 1e-3, size=(mk,)).astype(np.float32)
    bias = rng.normal(size=(mk,)).astype(np.float32)
    mm = jax.jit(functools.partial(_int8.int8_matmul_dequant, act="relu"))
    got, ms = _bench(lambda: mm(xq, wq, scale, bias), iters)
    ref = np.maximum(
        (xq.astype(np.int64) @ wq.astype(np.int64)) * scale + bias, 0.0)
    flops = 2.0 * mk * mk * mk + 3.0 * mk * mk
    bytes_ = float(2 * mk * mk + 4 * mk * mk + 8 * mk)
    rows.append(_row("int8_matmul", f"{mk}x{mk}x{mk}", ms, flops, bytes_,
                     jnp.abs(got - ref).max(), 1e-2))

    # -- int8 conv with fp32 per-channel dequant epilogue ---------------------
    xq4 = rng.integers(-127, 128, size=(n, hw, hw, ch), dtype=np.int8)
    wq4 = rng.integers(-127, 128, size=(ch, ch, kh, kw), dtype=np.int8)
    cscale = rng.uniform(1e-4, 1e-3, size=(ch,)).astype(np.float32)
    conv8 = jax.jit(functools.partial(_int8.int8_conv2d_dequant,
                                      stride=(1, 1), padding=(1, 1),
                                      act="relu"))
    got, ms = _bench(lambda: conv8(xq4, wq4, cscale, bias[:ch]), iters)
    ref = jax.nn.relu(jax.lax.conv_general_dilated(
        xq4.astype(np.float32),
        jnp.transpose(wq4, (2, 3, 1, 0)).astype(jnp.float32),
        (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC")) * cscale + bias[:ch])
    flops, bytes_ = _int8.int8_cost(n, hw, hw, ch, ch, kh, kw, in_h=hw,
                                    in_w=hw)
    rows.append(_row("int8_conv2d", f"{n}x{hw}x{hw}x{ch} k{kh}", ms,
                     flops, bytes_, jnp.abs(got - ref).max(), 1e-2))
    return rows


def _selfcheck(result) -> int:
    keys = {"kernel", "shape", "ms", "flops", "bytes", "gflops_s", "gb_s",
            "intensity", "max_abs_err", "tol"}
    names = {r["kernel"] for r in result["kernels"]}
    want = {"conv2d_bn_act", "bn_act_train", "max_pool2d", "avg_pool2d",
            "int8_matmul", "int8_conv2d"}
    if names != want:
        print(f"kernelbench selfcheck: kernel set {sorted(names)} != "
              f"{sorted(want)}", file=sys.stderr)
        return 1
    for r in result["kernels"]:
        if set(r) != keys:
            print(f"kernelbench selfcheck: bad row keys in {r['kernel']}",
                  file=sys.stderr)
            return 1
        if not (r["flops"] > 0 and r["bytes"] > 0 and r["ms"] >= 0):
            print(f"kernelbench selfcheck: non-positive cost in "
                  f"{r['kernel']}", file=sys.stderr)
            return 1
    print("kernelbench selfcheck: OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.kernelbench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--iters", type=int, default=5,
                        help="timed reps per kernel (median reported)")
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--hw", type=int, default=16,
                        help="spatial size of the conv/pool inputs")
    parser.add_argument("--ch", type=int, default=32,
                        help="channel count (conv C=O)")
    parser.add_argument("--mk", type=int, default=128,
                        help="int8 matmul M=K=N")
    parser.add_argument("--selfcheck", action="store_true",
                        help="tiny shapes + schema/parity gate; rides tier-1")
    args = parser.parse_args(argv)

    if args.selfcheck:
        args.iters, args.batch, args.hw, args.ch, args.mk = 1, 1, 8, 8, 16

    import jax

    from paddle_tpu.ops.pallas import config as _pcfg

    rows = run_bench(args.iters, args.batch, args.hw, args.ch, args.mk)
    result = {
        "backend": jax.default_backend(),
        "interpret": not _pcfg.backend_is_tpu(),
        "iters": args.iters,
        "kernels": rows,
    }
    if args.selfcheck:
        rc = _selfcheck(result)
    else:
        rc = 0
    print(json.dumps(result, sort_keys=True))
    bad = [r["kernel"] for r in result["kernels"]
           if r["max_abs_err"] > r["tol"]]
    if bad:
        print(f"kernelbench: parity FAILED for {bad}", file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
