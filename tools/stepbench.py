"""Steady-state Executor step micro-benchmark: host overhead of the
dispatch path, donation+async fast path vs today's copy+sync path.

Builds a tiny static program (one hidden fc + SGD step), runs N
steady-state steps in two modes, and prints exactly ONE JSON line:

  * ``fast``  — ``donate_state=1`` + ``return_numpy=False``: the state
    pytree stays device-resident and chained step to step, the PRNG fold
    happens inside the compiled function, and the fetch comes back as an
    unmaterialized ``jax.Array``, so ``Executor.run`` returns as soon as
    XLA has the step enqueued.  Host cost = the Python rim only.  (On
    TPU/GPU the flag additionally donates the state buffers; on CPU
    donation is skipped because XLA:CPU executes donated computations
    synchronously — see ``executor._donation_async_safe``.)
  * ``sync``  — ``donate_state=0`` + ``return_numpy=True``: every step
    round-trips a fresh copy of the state and forces the fetch through
    ``np.asarray`` (a blocking device sync), today's default-copy
    semantics.

``host_ms_*`` is the median wall time of one ``Executor.run`` call in
steady state (after warmup, compile excluded).  ``speedup`` is
``host_ms_sync / host_ms_fast`` — the per-step host overhead reduction the
fast path buys.  ``parity`` confirms both modes produced identical losses
(donation does not change math).  The ``metrics`` flag is forced off inside
the timed region so the instrumented step_time sync (see
``executor.step_time_ms``) does not serialize the fast path.

Two optional extra modes ride the same JSON line:

  * ``--mesh N`` — run the SHARDED fast path too: the same program compiled
    through ``CompiledProgram.with_sharding`` on an N-device dp mesh (feeds
    batch-sharded, state donated where the platform allows), reporting
    ``host_ms_sharded`` — the per-step host rim of the multi-device dispatch
    — next to the single-device numbers.  On CPU hosts the virtual device
    count is forced up before jax imports.
  * ``--cache [DIR]`` — measure the persistent AOT executable cache
    (``static/compile_cache.py``): first run against an empty DIR compiles
    and stores (``cold_start_ms``), a second run from a fresh Executor
    deserializes the stored executable (``warm_start_ms``, ``cache_hits``),
    skipping Python tracing/lowering entirely.  DIR defaults to a
    temp directory.  Both runs share ONE Program object: the global
    unique-name counter makes a rebuilt program fingerprint-different
    within a process (fresh processes regenerate identical names, which is
    the real cross-process warm-start story — see tests).

Usage:
    python -m tools.stepbench [--steps N] [--batch B] [--hidden H]
                              [--mesh N] [--cache [DIR]]
    python -m tools.stepbench --selfcheck     # smoke: rides tier-1
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time


def _ensure_cpu_devices(n: int) -> None:
    """Must run BEFORE jax imports: on CPU-only hosts, force enough virtual
    XLA devices for an N-way mesh (no-op if jax is already in, e.g. when a
    harness exported its own XLA_FLAGS)."""
    if "jax" in sys.modules:
        return
    env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in env:
        os.environ["XLA_FLAGS"] = (
            env + f" --xla_force_host_platform_device_count={n}").strip()


def _run_mode(donate: bool, async_dispatch: bool, steps: int, batch: int,
              hidden: int):
    """Fresh program + scope per mode; returns (median_host_ms, losses)."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    scope = static.Scope()
    saved = flags.get_flags(["donate_state", "metrics"])
    try:
        flags.set_flags({"donate_state": donate, "metrics": False})
        with static.program_guard(main, startup), static.scope_guard(scope):
            x = L.data("x", [hidden])
            y = L.data("y", [1])
            h = L.fc(x, hidden, act="relu")
            pred = L.fc(h, 1)
            loss = L.mean(L.square(L.elementwise_sub(pred, y)))
            static.optimizer.SGD(learning_rate=0.01).minimize(loss)

            exe = static.Executor()
            exe.run(startup)
            rng = np.random.default_rng(0)
            feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
                    "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}
            fetch = [loss]
            return_numpy = not async_dispatch
            for _ in range(3):  # warmup: compile + settle the caches
                out = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=return_numpy)
            np.asarray(out[0])  # drain warmup dispatches

            host_ms, losses = [], []
            for _ in range(steps):
                t0 = time.perf_counter()
                out = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=return_numpy)
                host_ms.append((time.perf_counter() - t0) * 1000.0)
                losses.append(out[0])
            # materialize at the end only — the async mode's device work
            # drains here, off the per-call host clock
            losses = [float(np.asarray(l)) for l in losses]
        return statistics.median(host_ms), losses
    finally:
        flags.set_flags(saved)


def _run_sharded(steps: int, batch: int, hidden: int, n_dev: int):
    """Sharded fast path on an N-device dp mesh (global batch, feeds
    batch-sharded, state replicated); returns (median_host_ms, losses)."""
    import jax
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.parallel.mesh import DP_AXIS
    from paddle_tpu.static import layers as L

    devs = jax.devices()[:n_dev]
    if len(devs) < n_dev:
        raise SystemExit(
            f"--mesh {n_dev}: only {len(devs)} device(s) visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
            "before python starts, or lower --mesh)")
    mesh = jax.sharding.Mesh(np.asarray(devs), (DP_AXIS,))

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    scope = static.Scope()
    saved = flags.get_flags(["donate_state", "metrics"])
    try:
        flags.set_flags({"donate_state": True, "metrics": False})
        with static.program_guard(main, startup), static.scope_guard(scope):
            x = L.data("x", [hidden])
            y = L.data("y", [1])
            h = L.fc(x, hidden, act="relu")
            pred = L.fc(h, 1)
            loss = L.mean(L.square(L.elementwise_sub(pred, y)))
            static.optimizer.SGD(learning_rate=0.01).minimize(loss)

            exe = static.Executor()
            exe.run(startup)
            compiled = static.CompiledProgram(main).with_sharding(mesh=mesh)
            rng = np.random.default_rng(0)
            feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
                    "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}
            for _ in range(3):
                out = exe.run(compiled, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            np.asarray(out[0])

            host_ms, losses = [], []
            for _ in range(steps):
                t0 = time.perf_counter()
                out = exe.run(compiled, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                host_ms.append((time.perf_counter() - t0) * 1000.0)
                losses.append(out[0])
            losses = [float(np.asarray(l)) for l in losses]
        return statistics.median(host_ms), losses
    finally:
        flags.set_flags(saved)


def _cache_bench(steps: int, batch: int, hidden: int, cache_dir: str) -> dict:
    """Cold vs warm start through the persistent executable cache.  ONE
    Program object, fresh Scope+Executor per run: run 1 populates the cache
    (miss), run 2 deserializes it (hit) without re-tracing."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.static import layers as L
    from paddle_tpu.utils import monitor

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with static.program_guard(main, startup):
        x = L.data("x", [hidden])
        y = L.data("y", [1])
        h = L.fc(x, hidden, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square(L.elementwise_sub(pred, y)))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
            "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}
    reg = monitor.default_registry()

    def counter(name):
        m = reg.get(name)
        return m.value() if m is not None else 0

    def one_run():
        scope = static.Scope()
        with static.scope_guard(scope):
            exe = static.Executor()
            exe.run(startup)
            t0 = time.perf_counter()
            out = exe.run(main, feed=feed, fetch_list=[loss],
                          return_numpy=False)
            start_ms = (time.perf_counter() - t0) * 1000.0
            losses = [float(np.asarray(out[0]))]
            for _ in range(max(0, steps - 1)):
                out = exe.run(main, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                losses.append(float(np.asarray(out[0])))
        return start_ms, losses

    saved = flags.get_flags(["donate_state", "metrics", "compile_cache_dir"])
    try:
        # metrics must be on for the hit/miss counters; only first-run wall
        # time (compile-dominated) is reported, so the per-step metric sync
        # does not pollute the numbers
        flags.set_flags({"donate_state": True, "metrics": True,
                         "compile_cache_dir": cache_dir})
        cold_ms, cold_losses = one_run()
        hits0 = counter("executor.compile_cache_hit")
        traces0 = counter("executor.traces")
        warm_ms, warm_losses = one_run()
        hits = counter("executor.compile_cache_hit") - hits0
        traces = counter("executor.traces") - traces0
    finally:
        flags.set_flags(saved)
    return {
        "cold_start_ms": round(cold_ms, 2),
        "warm_start_ms": round(warm_ms, 2),
        "cold_warm_ratio": round(cold_ms / warm_ms, 2) if warm_ms > 0 else None,
        "cache_hits": hits,
        "warm_traces": traces,  # 0 = the warm run never re-traced python
        "cache_parity": cold_losses == warm_losses,
        "cache_dir": cache_dir,
    }


def _run_autoplan(steps: int, batch: int, hidden: int, n_dev: int) -> dict:
    """Cost-model plan search over the bench program (parallel/autoplan.py):
    searches an N-device mesh, then measures the chosen plan's steady-state
    host step time next to the hand dp baseline.  Returned as flat numeric
    scalars so ``record.autoplan.*`` flows straight through benchdiff."""
    import jax
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.parallel import autoplan
    from paddle_tpu.parallel.sharding import ShardingPlan
    from paddle_tpu.static import layers as L

    devs = list(jax.devices()[:n_dev])
    if len(devs) < n_dev:
        raise SystemExit(
            f"--autoplan over {n_dev} devices: only {len(devs)} visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
            "before python starts)")

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with static.program_guard(main, startup):
        x = L.data("x", [hidden])
        y = L.data("y", [1])
        h = L.fc(x, hidden, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square(L.elementwise_sub(pred, y)))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.default_rng(0)
    feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
            "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}

    choice = autoplan.search(
        main, devices=devs,
        feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=(loss.name,))
    best = choice.ranked[0]

    def run_plan(plan):
        scope = static.Scope()
        with static.scope_guard(scope):
            exe = static.Executor()
            exe.run(startup)
            compiled = static.CompiledProgram(main).with_sharding(plan=plan)
            for _ in range(3):
                out = exe.run(compiled, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            np.asarray(out[0])
            host_ms = []
            for _ in range(steps):
                t0 = time.perf_counter()
                out = exe.run(compiled, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                host_ms.append((time.perf_counter() - t0) * 1000.0)
            final = float(np.asarray(out[0]))
        return statistics.median(host_ms), final

    saved = flags.get_flags(["donate_state", "metrics"])
    try:
        flags.set_flags({"donate_state": True, "metrics": False})
        auto_ms, _ = run_plan(best.plan)
        dp_ms, _ = run_plan(ShardingPlan(devices=devs, donate=False))
    finally:
        flags.set_flags(saved)

    return {
        "search_ms": round(choice.search_ms, 2),
        "candidates_ok": len(choice.ranked),
        "candidates_pruned": len(choice.pruned),
        "best_score_ms": round(best.score, 6),
        "best_comm_kb": round(
            best.corrected.get("comm_bytes", 0.0) / 1024.0, 3),
        "step_ms_auto": round(auto_ms, 4),
        "step_ms_dp": round(dp_ms, 4),
    }


def _run_profile(steps: int, batch: int, hidden: int) -> dict:
    """xprof roofline block for the bench program: a separate short run
    with metrics ON (the timed modes force metrics off, so this pass owns
    the step_time_ms anchor), condensed via ``xprof.summarize`` — coverage,
    MFU, drift, top regions and the memory-bound ones by name."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.static import layers as L
    from paddle_tpu.utils import xprof

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    scope = static.Scope()
    saved = flags.get_flags(["metrics"])
    try:
        flags.set_flags({"metrics": True})
        with static.program_guard(main, startup), static.scope_guard(scope):
            x = L.data("x", [hidden])
            y = L.data("y", [1])
            h = L.fc(x, hidden, act="relu")
            pred = L.fc(h, 1)
            loss = L.mean(L.square(L.elementwise_sub(pred, y)))
            static.optimizer.SGD(learning_rate=0.01).minimize(loss)

            exe = static.Executor()
            exe.run(startup)
            rng = np.random.default_rng(0)
            feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
                    "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}
            for _ in range(max(2, min(steps, 8))):
                exe.run(main, feed=feed, fetch_list=[loss])
            report = exe.xprof_report(main)
        return xprof.summarize(report)
    finally:
        flags.set_flags(saved)


def run_bench(steps: int = 50, batch: int = 64, hidden: int = 256,
              mesh: int = 0, cache_dir=None, profile: bool = False,
              autoplan: int = 0) -> dict:
    import jax

    fast_ms, fast_losses = _run_mode(donate=True, async_dispatch=True,
                                     steps=steps, batch=batch, hidden=hidden)
    sync_ms, sync_losses = _run_mode(donate=False, async_dispatch=False,
                                     steps=steps, batch=batch, hidden=hidden)
    result = {
        "metric": "executor_step_host_overhead",
        "unit": "ms/step (median host time in Executor.run)",
        "host_ms_fast": round(fast_ms, 4),
        "host_ms_sync": round(sync_ms, 4),
        "speedup": round(sync_ms / fast_ms, 3) if fast_ms > 0 else None,
        "parity": fast_losses == sync_losses,
        "loss_final": fast_losses[-1] if fast_losses else None,
        "steps": steps, "batch": batch, "hidden": hidden,
        "platform": jax.devices()[0].platform,
    }
    if mesh and mesh > 1:
        sharded_ms, sharded_losses = _run_sharded(
            steps=steps, batch=batch, hidden=hidden, n_dev=mesh)
        result["host_ms_sharded"] = round(sharded_ms, 4)
        result["mesh_devices"] = mesh
        # different XLA executables (GSPMD vs single-device) differ in ulps;
        # assert closeness at the DP tolerance, not bitwise
        result["sharded_parity"] = all(
            abs(a - b) <= 2e-4 * max(1.0, abs(b))
            for a, b in zip(sharded_losses, fast_losses))
    if cache_dir is not None:
        result.update(_cache_bench(steps=min(steps, 8), batch=batch,
                                   hidden=hidden, cache_dir=cache_dir))
    if profile:
        result["roofline"] = _run_profile(steps=steps, batch=batch,
                                          hidden=hidden)
    if autoplan and autoplan > 1:
        # under "record" so benchdiff's nested-scalar extractor picks the
        # block up as autoplan.* metrics (see tools/benchdiff.py)
        result["record"] = {"autoplan": _run_autoplan(
            steps=steps, batch=batch, hidden=hidden, n_dev=autoplan)}
    return result


def selfcheck() -> int:
    """Smoke for tier-1: tiny run covering all three modes — donation
    parity, a 2-device sharded pass, and a cache cold/warm round-trip."""
    _ensure_cpu_devices(2)
    with tempfile.TemporaryDirectory(prefix="pdtpu_stepbench_cc_") as cc:
        r = run_bench(steps=8, batch=8, hidden=32, mesh=2, cache_dir=cc,
                      profile=True, autoplan=2)
    ok = True
    ap = (r.get("record") or {}).get("autoplan") or {}
    if not (ap.get("candidates_ok", 0) > 0 and ap.get("step_ms_auto", 0) > 0
            and ap.get("search_ms", 0) > 0):
        print(f"selfcheck: bad autoplan block {ap!r}", file=sys.stderr)
        ok = False
    roof = r.get("roofline") or {}
    if not (roof.get("attribution_coverage", 0) >= 0.9):
        print(f"selfcheck: roofline attribution coverage "
              f"{roof.get('attribution_coverage')} < 0.9", file=sys.stderr)
        ok = False
    if not roof.get("top_regions"):
        print("selfcheck: roofline block has no top_regions",
              file=sys.stderr)
        ok = False
    for k in ("host_ms_fast", "host_ms_sync", "speedup", "parity",
              "host_ms_sharded", "sharded_parity", "cold_start_ms",
              "warm_start_ms", "cache_parity"):
        if r.get(k) is None:
            print(f"selfcheck: missing/None field {k!r}", file=sys.stderr)
            ok = False
    if not r.get("parity"):
        print("selfcheck: donated and undonated losses diverged",
              file=sys.stderr)
        ok = False
    if not r.get("sharded_parity"):
        print("selfcheck: sharded losses diverged from single-device "
              "fast path beyond tolerance", file=sys.stderr)
        ok = False
    if not r.get("cache_parity"):
        print("selfcheck: warm-cache losses diverged from cold run",
              file=sys.stderr)
        ok = False
    if not r.get("cache_hits"):
        print("selfcheck: warm run produced no compile-cache hits",
              file=sys.stderr)
        ok = False
    if r.get("warm_traces"):
        print(f"selfcheck: warm run re-traced python "
              f"({r['warm_traces']} traces)", file=sys.stderr)
        ok = False
    if ok and not (r["host_ms_fast"] > 0 and r["host_ms_sync"] > 0
                   and r["host_ms_sharded"] > 0):
        print("selfcheck: non-positive timings", file=sys.stderr)
        ok = False
    print(f"stepbench selfcheck: {'OK' if ok else 'FAILED'} "
          f"(fast={r['host_ms_fast']}ms sync={r['host_ms_sync']}ms "
          f"sharded={r['host_ms_sharded']}ms speedup={r['speedup']}x "
          f"parity={r['parity']} cold={r['cold_start_ms']}ms "
          f"warm={r['warm_start_ms']}ms hits={r['cache_hits']})")
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.stepbench",
        description="Steady-state Executor step host-overhead benchmark "
                    "(donation + async dispatch on vs off).")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--mesh", type=int, default=0, metavar="N",
                        help="also run the sharded fast path on an N-device "
                             "dp mesh (reports host_ms_sharded)")
    parser.add_argument("--cache", nargs="?", const="", default=None,
                        metavar="DIR",
                        help="also measure the persistent executable cache: "
                             "cold vs warm start against DIR (default: a "
                             "temp directory)")
    parser.add_argument("--profile", action="store_true",
                        help="also attach an xprof roofline block (coverage, "
                             "MFU, top regions; see tools/xprof.py)")
    parser.add_argument("--autoplan", type=int, default=0, metavar="N",
                        help="also run the cost-model plan search over an "
                             "N-device mesh and measure the chosen plan "
                             "(benchdiff-consumable record.autoplan block)")
    parser.add_argument("--selfcheck", action="store_true",
                        help="tiny smoke run with field/parity checks")
    args = parser.parse_args(argv)
    if args.selfcheck:
        return selfcheck()
    if max(args.mesh, args.autoplan) > 1:
        _ensure_cpu_devices(max(args.mesh, args.autoplan))
    if args.cache == "":
        with tempfile.TemporaryDirectory(prefix="pdtpu_stepbench_cc_") as cc:
            r = run_bench(steps=args.steps, batch=args.batch,
                          hidden=args.hidden, mesh=args.mesh, cache_dir=cc,
                          profile=args.profile, autoplan=args.autoplan)
    else:
        r = run_bench(steps=args.steps, batch=args.batch, hidden=args.hidden,
                      mesh=args.mesh, cache_dir=args.cache,
                      profile=args.profile, autoplan=args.autoplan)
    print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
