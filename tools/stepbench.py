"""Steady-state Executor step micro-benchmark: host overhead of the
dispatch path, donation+async fast path vs today's copy+sync path.

Builds a tiny static program (one hidden fc + SGD step), runs N
steady-state steps in two modes, and prints exactly ONE JSON line:

  * ``fast``  — ``donate_state=1`` + ``return_numpy=False``: the state
    pytree stays device-resident and chained step to step, the PRNG fold
    happens inside the compiled function, and the fetch comes back as an
    unmaterialized ``jax.Array``, so ``Executor.run`` returns as soon as
    XLA has the step enqueued.  Host cost = the Python rim only.  (On
    TPU/GPU the flag additionally donates the state buffers; on CPU
    donation is skipped because XLA:CPU executes donated computations
    synchronously — see ``executor._donation_async_safe``.)
  * ``sync``  — ``donate_state=0`` + ``return_numpy=True``: every step
    round-trips a fresh copy of the state and forces the fetch through
    ``np.asarray`` (a blocking device sync), today's default-copy
    semantics.

``host_ms_*`` is the median wall time of one ``Executor.run`` call in
steady state (after warmup, compile excluded).  ``speedup`` is
``host_ms_sync / host_ms_fast`` — the per-step host overhead reduction the
fast path buys.  ``parity`` confirms both modes produced identical losses
(donation does not change math).  The ``metrics`` flag is forced off inside
the timed region so the instrumented step_time sync (see
``executor.step_time_ms``) does not serialize the fast path.

Usage:
    python -m tools.stepbench [--steps N] [--batch B] [--hidden H] [--json]
    python -m tools.stepbench --selfcheck     # smoke: rides tier-1
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _run_mode(donate: bool, async_dispatch: bool, steps: int, batch: int,
              hidden: int):
    """Fresh program + scope per mode; returns (median_host_ms, losses)."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    main.random_seed = 7
    startup.random_seed = 7
    scope = static.Scope()
    saved = flags.get_flags(["donate_state", "metrics"])
    try:
        flags.set_flags({"donate_state": donate, "metrics": False})
        with static.program_guard(main, startup), static.scope_guard(scope):
            x = L.data("x", [hidden])
            y = L.data("y", [1])
            h = L.fc(x, hidden, act="relu")
            pred = L.fc(h, 1)
            loss = L.mean(L.square(L.elementwise_sub(pred, y)))
            static.optimizer.SGD(learning_rate=0.01).minimize(loss)

            exe = static.Executor()
            exe.run(startup)
            rng = np.random.default_rng(0)
            feed = {"x": rng.normal(0, 1, (batch, hidden)).astype(np.float32),
                    "y": rng.normal(0, 1, (batch, 1)).astype(np.float32)}
            fetch = [loss]
            return_numpy = not async_dispatch
            for _ in range(3):  # warmup: compile + settle the caches
                out = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=return_numpy)
            np.asarray(out[0])  # drain warmup dispatches

            host_ms, losses = [], []
            for _ in range(steps):
                t0 = time.perf_counter()
                out = exe.run(main, feed=feed, fetch_list=fetch,
                              return_numpy=return_numpy)
                host_ms.append((time.perf_counter() - t0) * 1000.0)
                losses.append(out[0])
            # materialize at the end only — the async mode's device work
            # drains here, off the per-call host clock
            losses = [float(np.asarray(l)) for l in losses]
        return statistics.median(host_ms), losses
    finally:
        flags.set_flags(saved)


def run_bench(steps: int = 50, batch: int = 64, hidden: int = 256) -> dict:
    import jax

    fast_ms, fast_losses = _run_mode(donate=True, async_dispatch=True,
                                     steps=steps, batch=batch, hidden=hidden)
    sync_ms, sync_losses = _run_mode(donate=False, async_dispatch=False,
                                     steps=steps, batch=batch, hidden=hidden)
    return {
        "metric": "executor_step_host_overhead",
        "unit": "ms/step (median host time in Executor.run)",
        "host_ms_fast": round(fast_ms, 4),
        "host_ms_sync": round(sync_ms, 4),
        "speedup": round(sync_ms / fast_ms, 3) if fast_ms > 0 else None,
        "parity": fast_losses == sync_losses,
        "loss_final": fast_losses[-1] if fast_losses else None,
        "steps": steps, "batch": batch, "hidden": hidden,
        "platform": jax.devices()[0].platform,
    }


def selfcheck() -> int:
    """Smoke for tier-1: tiny run, sane fields, donation parity."""
    r = run_bench(steps=8, batch=8, hidden=32)
    ok = True
    for k in ("host_ms_fast", "host_ms_sync", "speedup", "parity"):
        if r.get(k) is None:
            print(f"selfcheck: missing/None field {k!r}", file=sys.stderr)
            ok = False
    if not r.get("parity"):
        print("selfcheck: donated and undonated losses diverged",
              file=sys.stderr)
        ok = False
    if ok and not (r["host_ms_fast"] > 0 and r["host_ms_sync"] > 0):
        print("selfcheck: non-positive timings", file=sys.stderr)
        ok = False
    print(f"stepbench selfcheck: {'OK' if ok else 'FAILED'} "
          f"(fast={r['host_ms_fast']}ms sync={r['host_ms_sync']}ms "
          f"speedup={r['speedup']}x parity={r['parity']})")
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.stepbench",
        description="Steady-state Executor step host-overhead benchmark "
                    "(donation + async dispatch on vs off).")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--selfcheck", action="store_true",
                        help="tiny smoke run with field/parity checks")
    args = parser.parse_args(argv)
    if args.selfcheck:
        return selfcheck()
    print(json.dumps(run_bench(steps=args.steps, batch=args.batch,
                               hidden=args.hidden)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
