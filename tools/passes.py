"""passes — CLI front-end for the verified graph-rewrite pipeline.

Runs the ``static/passes.py`` pass manager over a Program and prints a
per-pass diff report (op counts, fusions, transposes cancelled).  Every
rewrite runs under the VerifiedRewrite contract: the fetch interface is
proven preserved (PV011 on violation) and the full program checker re-runs
on the result; ``--verify`` additionally executes original vs rewritten
with identical feeds/state and compares fetches (bitwise for ints,
tolerance for floats).

Usage::

    python -m tools.passes                      # demo inference net, report
    python -m tools.passes --verify             # + execution golden parity
    python -m tools.passes --pipeline cse,dce   # a specific pass list
    python -m tools.passes --model DIR          # a saved inference model
    python -m tools.passes --format json
    python -m tools.passes --selfcheck          # CI probe (rides tier-1)

Without ``--model`` the CLI runs against a built-in demo: a small
inference-mode conv+BN+relu / fc+gelu tower (the exact patterns the fusion
passes target) with a duplicated subexpression and a dead branch seeded so
constant folding, CSE, and DCE all have work to do.  ``--selfcheck``
asserts the pipeline fuses both patterns, strictly shrinks the op count,
holds golden parity, and that a deliberately interface-breaking rewrite
trips PV011 — then prints ``passes selfcheck: OK``.
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _build_demo():
    """(main, startup, feed, fetch_names): inference conv tower with
    fusible patterns plus dead/duplicate ops for the cleanup passes."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = L.data("img", [3, 16, 16])
        c1 = L.conv2d(img, 8, 3, padding=1)
        b1 = L.batch_norm(c1, act="relu", is_test=True)
        p1 = L.pool2d(b1, 2)
        flat = L.flatten(p1)
        h = L.fc(flat, 32, act="gelu")
        d1 = L.scale(h, 2.0)
        d2 = L.scale(h, 2.0)                 # duplicate subexpression
        merged = L.elementwise_add(d1, d2)
        L.scale(merged, 3.0)                 # dead: never fetched
        base = L.fill_constant([1], "float32", 2.0)
        off = L.scale(base, 0.5)             # constant-foldable
        out = L.elementwise_add(L.fc(merged, 10), off)
    feed = {"img": np.random.default_rng(0).normal(
        0, 1, (4, 3, 16, 16)).astype(np.float32)}
    return main, startup, feed, [out.name]


def _demo_feed_for(program, feed_names, batch=4):
    """Random feeds shaped from the program's data vars (-1 -> batch)."""
    rng = np.random.default_rng(0)
    block = program.global_block()
    feed = {}
    for name in feed_names:
        v = block.var(name)
        shape = tuple(batch if d == -1 else int(d) for d in v.shape)
        dt = np.dtype(v.dtype)
        if dt.kind in ("i", "u"):
            feed[name] = rng.integers(0, 2, shape).astype(dt)
        else:
            feed[name] = rng.normal(0, 1, shape).astype(dt)
    return feed


def _run(program, startup, feed, fetch_names, pipeline, verify,
         scope=None):
    """Apply the pipeline; returns (report, parity|None, rewritten)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import passes as P

    pm = P.PassManager(pipeline)
    rewritten, report = pm.apply(program, feed_names=set(feed),
                                 fetch_names=fetch_names)
    parity = None
    if verify:
        if scope is None:
            scope = static.Scope()
            with static.scope_guard(scope):
                if startup is not None:
                    static.Executor().run(startup)
        state = {k: np.asarray(scope.find_var(k)) for k in scope.keys()}
        parity = P.golden_parity(program, rewritten, feed, fetch_names,
                                 state=state, rtol=1e-4, atol=1e-5)
    return report, parity, rewritten


def selfcheck() -> int:
    """Assert the default pipeline earns its keep on the demo net and that
    verification actually rejects a broken rewrite.  Non-zero exit on any
    deviation — rides tier-1 via subprocess."""
    from paddle_tpu.static import passes as P

    main, startup, feed, fetch_names = _build_demo()
    report, parity, rewritten = _run(main, startup, feed, fetch_names,
                                     P.DEFAULT_PIPELINE, verify=True)
    print(report.to_text())
    types = [op.type for op in rewritten.global_block().ops]
    problems = []
    if "fused_conv2d_bn_act" not in types:
        problems.append("conv+BN+act did not fuse")
    if "fused_matmul_bias_act" not in types:
        problems.append("matmul+bias+act did not fuse")
    if report.ops_after >= report.ops_before:
        problems.append(f"op count did not shrink "
                        f"({report.ops_before} -> {report.ops_after})")
    if parity is None or not parity.ok:
        problems.append("golden parity failed: "
                        + (parity.to_text() if parity else "no report"))

    # a rewrite that breaks the fetch interface must trip PV011
    broken = main.clone()
    blk = broken.global_block()
    blk.remove_op(len(blk.ops) - 1)          # drop the fetch producer
    try:
        P.verify_rewrite(main, broken, feed_names=set(feed),
                         fetch_names=fetch_names)
        problems.append("PV011 did not fire on an interface-breaking "
                        "rewrite")
    except Exception as e:
        if "PV011" not in str(e):
            problems.append(f"broken rewrite raised without PV011: {e!r}")

    if problems:
        for p in problems:
            print(f"passes selfcheck: {p}", file=sys.stderr)
        return 1
    print(parity.to_text())
    print("passes selfcheck: OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.passes", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--pipeline", default="default",
                        help="comma-separated pass list, or 'default'")
    parser.add_argument("--verify", action="store_true",
                        help="execute original vs rewritten and compare "
                        "(bitwise ints / tolerance floats)")
    parser.add_argument("--model", default=None, metavar="DIR",
                        help="run over a saved inference model instead of "
                        "the built-in demo")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--selfcheck", action="store_true",
                        help="CI probe: assert fusions, parity, and PV011 "
                        "on the built-in demo")
    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    from paddle_tpu.static import passes as P

    pipeline = (P.DEFAULT_PIPELINE if args.pipeline in ("default", "1", "")
                else tuple(s.strip() for s in args.pipeline.split(",")
                           if s.strip()))

    scope = None
    startup = None
    if args.model:
        import paddle_tpu.static as static

        scope = static.Scope()
        with static.scope_guard(scope):
            program, feed_names, fetch_names = static.load_inference_model(
                args.model, static.Executor())
        feed = _demo_feed_for(program, feed_names)
    else:
        program, startup, feed, fetch_names = _build_demo()

    report, parity, rewritten = _run(program, startup, feed, fetch_names,
                                     pipeline, args.verify, scope=scope)

    if args.format == "json":
        payload = {
            "fingerprint": report.fingerprint,
            "ops_before": report.ops_before,
            "ops_after": report.ops_after,
            "elapsed_ms": report.elapsed_ms,
            "skipped": report.skipped,
            "passes": [{"name": p.name, "changed": p.changed,
                        "ops_before": p.ops_before,
                        "ops_after": p.ops_after,
                        "stats": {k: v for k, v in p.stats.items()
                                  if k != "changed"}}
                       for p in report.passes],
            "parity": None if parity is None else {
                "ok": parity.ok, "max_abs_err": parity.max_abs_err,
                "state_max_err": parity.state_max_err,
                "message": parity.message},
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(report.to_text())
        print("rewritten ops: "
              + " ".join(op.type for op in rewritten.global_block().ops))
        if parity is not None:
            print(parity.to_text())
    if parity is not None and not parity.ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
