"""Merge, inspect, and pretty-print paddle_tpu trace artifacts.

A distributed run (``python -m paddle_tpu.distributed.launch --trace_dir d``)
leaves one chrome trace per rank (``trace.rank<r>.json``) and, on crash or
SIGTERM, a flight-recorder dump (``flight.rank<r>.json``).  tracecat is the
one-command consumer of those artifacts:

merge
    stitch per-rank chrome traces into a single chrome://tracing /
    Perfetto-loadable timeline.  Each input file becomes one process row:
    ``pid`` is rewritten to the rank (parsed from a ``rank<N>`` token in the
    filename, else the argument position) and ``ph:"M"`` process_name /
    process_sort_index metadata events are inserted so the UI labels and
    orders the rows.

tree
    text rendering of the span forest (``ph:"X"`` events nested by
    containment per pid/tid) — a poor man's trace viewer for terminals.

flight
    pretty-print one or more flight-recorder dumps, merged and sorted by
    timestamp, with trace/span ids shortened for humans.

Usage::

    python -m tools.tracecat merge d/trace.rank*.json --out merged.json
    python -m tools.tracecat tree  merged.json
    python -m tools.tracecat flight d/flight.rank*.json
    python -m tools.tracecat --selfcheck        # synthetic end-to-end smoke

``--selfcheck`` generates two synthetic rank traces in a temp dir, merges
them, validates the result (valid JSON, both pids present, process_name
metadata, spans preserved) and exits 0/1 — cheap enough for tier-1 CI.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_RANK_RE = re.compile(r"rank(\d+)")


# ---------------------------------------------------------------------------
# loading


def _load_events(path: str) -> List[dict]:
    """Read a chrome trace (object-with-traceEvents or bare array form)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError(f"{path}: not a chrome trace (got {type(doc).__name__})")
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return [e for e in events if isinstance(e, dict)]


def _rank_of(path: str, position: int) -> int:
    m = _RANK_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else position


# ---------------------------------------------------------------------------
# merge


def merge_traces(paths: List[str]) -> dict:
    """Merge per-rank chrome traces into one timeline keyed by pid=rank."""
    merged: List[dict] = []
    seen_ranks = set()
    for pos, path in enumerate(paths):
        rank = _rank_of(path, pos)
        while rank in seen_ranks:  # duplicate rank tokens: fall back to slot
            rank += 1
        seen_ranks.add(rank)
        events = _load_events(path)
        body = []
        for e in events:
            if e.get("ph") == "M":
                continue  # re-emitted below with the merged-view rank
            e = dict(e)
            e["pid"] = rank
            body.append(e)
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"paddle_tpu rank {rank} "
                                        f"({os.path.basename(path)})"}})
        merged.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                       "args": {"sort_index": rank}})
        merged.extend(body)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# tree view


def _span_tree_lines(events: List[dict]) -> List[str]:
    """Nest ph:"X" events by interval containment within each (pid, tid)."""
    lanes: Dict[Tuple[object, object], List[dict]] = {}
    names: Dict[object, str] = {}
    for e in events:
        ph = e.get("ph")
        if ph == "M" and e.get("name") == "process_name":
            names[e.get("pid")] = e.get("args", {}).get("name", "")
        if ph != "X":
            continue
        lanes.setdefault((e.get("pid", 0), e.get("tid", 0)), []).append(e)

    lines: List[str] = []
    for (pid, tid) in sorted(lanes, key=lambda k: (str(k[0]), str(k[1]))):
        label = names.get(pid) or f"pid {pid}"
        lines.append(f"{label} / tid {tid}")
        stack: List[float] = []  # end timestamps of open ancestors
        spans = sorted(lanes[(pid, tid)],
                       key=lambda e: (e.get("ts", 0), -e.get("dur", 0)))
        for e in spans:
            ts = float(e.get("ts", 0))
            dur = float(e.get("dur", 0))
            while stack and ts >= stack[-1]:
                stack.pop()
            indent = "  " * (len(stack) + 1)
            lines.append(f"{indent}{e.get('name', '?')}  "
                         f"[{dur / 1000.0:.3f} ms @ {ts / 1000.0:.3f}]")
            stack.append(ts + dur)
    return lines


# ---------------------------------------------------------------------------
# flight recorder


def _short(ident: Optional[str], n: int = 8) -> str:
    return (ident or "-")[:n]


def _flight_lines(paths: List[str]) -> List[str]:
    records = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        meta = doc.get("meta", {}) if isinstance(doc, dict) else {}
        events = doc.get("events", []) if isinstance(doc, dict) else doc
        rank = meta.get("rank", _rank_of(path, 0))
        for e in events:
            if isinstance(e, dict):
                records.append((rank, e))
    records.sort(key=lambda it: it[1].get("ts", 0.0))

    lines = []
    for rank, e in records:
        extras = {k: v for k, v in e.items()
                  if k not in ("ts", "kind", "name", "rank", "thread",
                               "trace_id", "span_id", "parent_id")}
        extra = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
        lines.append(f"[{e.get('ts', 0.0):.6f}] r{rank} "
                     f"{e.get('kind', '?'):<12} {e.get('name', ''):<28} "
                     f"trace={_short(e.get('trace_id'))} "
                     f"span={_short(e.get('span_id'))}"
                     f"{('  ' + extra) if extra else ''}")
    return lines


# ---------------------------------------------------------------------------
# selfcheck


def _synthetic_trace(rank: int, base_ts: int) -> dict:
    pid = os.getpid()
    return {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": f"synthetic rank {rank}"}},
        {"name": "executor::run", "ph": "X", "pid": pid, "tid": 1,
         "ts": base_ts, "dur": 900, "args": {"rank": rank}},
        {"name": "ps.rpc::pull", "ph": "X", "pid": pid, "tid": 1,
         "ts": base_ts + 100, "dur": 300, "args": {}},
        {"name": "executor.cache_hit", "ph": "C", "pid": pid, "tid": 0,
         "ts": base_ts + 950, "args": {"value": rank + 1}},
    ]}


def selfcheck() -> int:
    import tempfile
    tmp = tempfile.mkdtemp(prefix="tracecat_selfcheck_")
    paths = []
    for rank in (0, 1):
        p = os.path.join(tmp, f"trace.rank{rank}.json")
        with open(p, "w") as f:
            json.dump(_synthetic_trace(rank, 1000 + rank * 2000), f)
        paths.append(p)

    merged = merge_traces(paths)
    out = os.path.join(tmp, "merged.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    with open(out) as f:
        doc = json.load(f)  # must round-trip as valid JSON

    events = doc["traceEvents"]
    pids = {e.get("pid") for e in events if e.get("ph") == "X"}
    ok = True
    if pids != {0, 1}:
        print(f"selfcheck: merged pids {pids} != {{0, 1}}", file=sys.stderr)
        ok = False
    name_metas = [e for e in events
                  if e.get("ph") == "M" and e.get("name") == "process_name"]
    if {e.get("pid") for e in name_metas} != {0, 1}:
        print("selfcheck: missing process_name metadata", file=sys.stderr)
        ok = False
    spans = [e for e in events if e.get("ph") == "X"]
    if len(spans) != 4:
        print(f"selfcheck: expected 4 spans, got {len(spans)}",
              file=sys.stderr)
        ok = False
    tree = _span_tree_lines(events)
    if not any("ps.rpc::pull" in ln for ln in tree):
        print("selfcheck: span tree lost ps.rpc::pull", file=sys.stderr)
        ok = False
    print(f"tracecat selfcheck: {'OK' if ok else 'FAILED'} "
          f"({len(events)} merged events, {len(tree)} tree lines, {tmp})")
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tracecat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--selfcheck", action="store_true",
                        help="synthetic merge smoke test, exits 0/1")
    sub = parser.add_subparsers(dest="cmd")

    p_merge = sub.add_parser("merge", help="merge per-rank chrome traces")
    p_merge.add_argument("traces", nargs="+")
    p_merge.add_argument("--out", default=None,
                         help="output path (default: stdout)")
    p_merge.add_argument("--tree", action="store_true",
                         help="also print the span-tree view to stderr")

    p_tree = sub.add_parser("tree", help="span-tree text view of a trace")
    p_tree.add_argument("trace")

    p_flight = sub.add_parser("flight",
                              help="pretty-print flight-recorder dumps")
    p_flight.add_argument("dumps", nargs="+")

    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()
    if args.cmd is None:
        parser.print_usage(sys.stderr)
        return 2

    if args.cmd == "merge":
        merged = merge_traces(args.traces)
        text = json.dumps(merged, indent=1)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
            print(f"tracecat: wrote {len(merged['traceEvents'])} events "
                  f"from {len(args.traces)} ranks to {args.out}")
        else:
            print(text)
        if args.tree:
            for ln in _span_tree_lines(merged["traceEvents"]):
                print(ln, file=sys.stderr)
        return 0

    if args.cmd == "tree":
        for ln in _span_tree_lines(_load_events(args.trace)):
            print(ln)
        return 0

    if args.cmd == "flight":
        for ln in _flight_lines(args.dumps):
            print(ln)
        return 0

    return 2


if __name__ == "__main__":
    sys.exit(main())
