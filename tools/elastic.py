"""Inspect and dry-run elastic checkpoints (elastic/checkpoint.py).

The fault-tolerance analogue of proglint/metricsdump: one command that
answers "is this checkpoint intact, and what would restoring it onto a
different mesh actually move?" without touching the training job.

Usage::

    python -m tools.elastic inspect  CKPT_DIR [--step N] [--verify-shards]
    python -m tools.elastic reshard  CKPT_DIR --mesh dp=2 [--zero-stage N]
    python -m tools.elastic selfcheck [--json]

``inspect`` prints the digest-verified manifest for one step (default:
latest): step, source mesh, plan fingerprint, and a per-leaf table of
shape/dtype/spec/shards.  ``--verify-shards`` additionally re-hashes every
shard file against its recorded SHA-256.

``reshard`` is a dry run of an elastic resume at a new mesh shape: it
builds the target ShardingPlan, computes each leaf's target placement
(without loading any shard data), and reports which leaves would physically
reshard and how many bytes that moves — the cost report for an eviction
before you pay it.

``selfcheck`` forces 8 host devices, saves a ZeRO-3 dp=4 state, restores
it under a dp=2 plan, and verifies the gathered values are bitwise
identical with a nonzero reshard count — a tier-1-safe end-to-end probe of
the whole save → manifest → gather → re-place path.  Exits nonzero on any
mismatch.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys


def _force_host_devices(n: int = 8) -> None:
    """Before the first jax import: make XLA expose n host devices (the
    stepbench pattern) so dp meshes exist on a CPU-only machine."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def _parse_mesh_arg(spec: str):
    """'dp=2' or 'dp=2,tp=4' -> ordered {axis: size}."""
    axes = {}
    for part in spec.split(","):
        if "=" not in part:
            raise SystemExit(f"--mesh: expected axis=size, got {part!r}")
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def _build_mesh(axes):
    import numpy as np

    import jax
    from jax.sharding import Mesh

    n = 1
    for s in axes.values():
        n *= s
    devs = jax.devices()
    if len(devs) < n:
        raise SystemExit(
            f"mesh {axes} needs {n} devices, have {len(devs)} "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return Mesh(np.asarray(devs[:n]).reshape(tuple(axes.values())),
                tuple(axes.keys()))


# ---------------------------------------------------------------------------
# inspect
# ---------------------------------------------------------------------------

def _leaf_bytes(leaf) -> int:
    import numpy as np

    n = 1
    for d in leaf["shape"]:
        n *= int(d)
    return n * np.dtype(leaf.get("dtype", "float32")).itemsize


def cmd_inspect(args) -> int:
    from paddle_tpu.elastic import checkpoint as eckpt

    try:
        body = eckpt.load_manifest(args.ckpt_dir, args.step)
    except eckpt.CheckpointError as e:
        print(f"elastic: {e}", file=sys.stderr)
        return 1
    step = body["step"]
    print(f"checkpoint {args.ckpt_dir} step {step}")
    print(f"  schema:           {body['schema']}")
    print(f"  mesh:             {body['mesh']['axes'] or '(single host)'} "
          f"[{body['mesh']['fingerprint']}]")
    print(f"  plan fingerprint: {body['plan_fingerprint'] or '(none)'}")
    print(f"  prng key:         {body['prng_key'] or '(none)'}")
    print(f"  steps on disk:    {eckpt.list_steps(args.ckpt_dir)} "
          f"(latest={eckpt.latest_step(args.ckpt_dir)})")
    total = 0
    print(f"  leaves ({len(body['leaves'])}):")
    for leaf in body["leaves"]:
        total += _leaf_bytes(leaf)
        spec = leaf["spec"] or "replicated"
        print(f"    {leaf['name']:<32} {str(tuple(leaf['shape'])):<16} "
              f"{leaf['dtype']:<10} spec={spec} shards={len(leaf['shards'])}")
    print(f"  total state: {total} bytes")
    if args.verify_shards:
        sdir = os.path.join(args.ckpt_dir, f"step_{int(step):08d}")
        bad = 0
        for leaf in body["leaves"]:
            for sh in leaf["shards"]:
                fpath = os.path.join(sdir, sh["file"])
                try:
                    with open(fpath, "rb") as f:
                        ok = hashlib.sha256(f.read()).hexdigest() == sh["sha256"]
                except OSError:
                    ok = False
                if not ok:
                    bad += 1
                    print(f"elastic: shard digest mismatch: {fpath}",
                          file=sys.stderr)
        if bad:
            return 1
        print("  shard digests: all OK")
    return 0


# ---------------------------------------------------------------------------
# reshard dry run
# ---------------------------------------------------------------------------

def cmd_reshard(args) -> int:
    _force_host_devices()
    import numpy as np

    from paddle_tpu.elastic import checkpoint as eckpt
    from paddle_tpu.parallel.sharding import ShardingPlan

    try:
        body = eckpt.load_manifest(args.ckpt_dir, args.step)
    except eckpt.CheckpointError as e:
        print(f"elastic: {e}", file=sys.stderr)
        return 1
    axes = _parse_mesh_arg(args.mesh)
    mesh = _build_mesh(axes)
    plan = ShardingPlan(mesh=mesh, zero_stage=args.zero_stage)
    # placement only needs shapes: zero-copy broadcast views stand in for
    # the real leaves, no shard file is read
    fake = {leaf["name"]: np.broadcast_to(
                np.zeros((), dtype=leaf.get("dtype", "float32")),
                tuple(leaf["shape"]))
            for leaf in body["leaves"]}
    shardings = plan.state_shardings(fake, mesh)
    saved_axes = body["mesh"]["axes"]
    target_axes = {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
    moved_bytes = 0
    moved = []
    for leaf in body["leaves"]:
        tspec = eckpt._spec_to_json(shardings[leaf["name"]].spec)
        if (eckpt._placement_sig(saved_axes, leaf["spec"])
                != eckpt._placement_sig(target_axes, tspec)):
            moved.append((leaf["name"], leaf["spec"] or "replicated",
                          tspec or "replicated"))
            moved_bytes += _leaf_bytes(leaf)
    print(f"reshard dry run: step {body['step']} "
          f"{saved_axes or '(single host)'} -> {target_axes} "
          f"zero_stage={args.zero_stage}")
    print(f"  target plan: {plan.fingerprint()}")
    if not moved:
        print("  no leaf reshards (placements identical)")
    for name, old, new in moved:
        print(f"  reshard {name:<32} {old} -> {new}")
    print(f"  {len(moved)}/{len(body['leaves'])} leaves reshard, "
          f"{moved_bytes} bytes move")
    return 0


# ---------------------------------------------------------------------------
# selfcheck
# ---------------------------------------------------------------------------

def cmd_selfcheck(args) -> int:
    _force_host_devices()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from paddle_tpu.elastic import checkpoint as eckpt
    from paddle_tpu.parallel.mesh import DP_AXIS
    from paddle_tpu.parallel.sharding import ShardingPlan

    verdict = {"ok": False, "devices": jax.device_count()}
    try:
        rng = np.random.default_rng(0)
        state = {
            "w": rng.normal(size=(64, 16)).astype(np.float32),
            "b": rng.normal(size=(16,)).astype(np.float32),
            "step": np.float32(3.0),
        }

        def dp_plan(n):
            return ShardingPlan(
                mesh=Mesh(np.asarray(jax.devices()[:n]), (DP_AXIS,)),
                zero_stage=3)

        with tempfile.TemporaryDirectory() as d:
            eckpt.save_checkpoint(d, state, 7, plan=dp_plan(4))
            restored, meta = eckpt.restore_checkpoint(d, plan=dp_plan(2))
        mismatches = [k for k in state
                      if not np.array_equal(np.asarray(restored[k]), state[k])]
        verdict.update(
            step=meta["step"], resharded_leaves=meta["resharded_leaves"],
            saved_mesh=meta["mesh_axes"], mismatched_leaves=mismatches,
            ok=(not mismatches and meta["step"] == 7
                and meta["resharded_leaves"] > 0))
    except Exception as e:  # selfcheck reports, never tracebacks
        verdict["error"] = f"{type(e).__name__}: {e}"
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
    else:
        print(f"elastic selfcheck: {'OK' if verdict['ok'] else 'FAIL'} "
              f"({verdict})")
    return 0 if verdict["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.elastic", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect", help="print the digest-verified manifest")
    p.add_argument("ckpt_dir")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--verify-shards", action="store_true",
                   help="re-hash every shard file against the manifest")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("reshard",
                       help="dry-run a restore onto a different mesh")
    p.add_argument("ckpt_dir")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--mesh", required=True,
                   help="target mesh, e.g. dp=2 or dp=2,tp=2")
    p.add_argument("--zero-stage", type=int, default=0)
    p.set_defaults(fn=cmd_reshard)

    p = sub.add_parser("selfcheck",
                       help="end-to-end save/reshard-restore parity probe")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_selfcheck)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
