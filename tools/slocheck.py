"""Validate an SLO objective file against the metric inventory.

The SLO engine (paddle_tpu/utils/slo.py) accepts objective files in TOML
or JSON; a typo'd metric name, a bad comparator or an inverted window
pair would otherwise ship silently and the alert would simply never fire.
This tool is the pre-flight check:

* **structural** — the file parses, every SLO/Window field validates
  (op, objective_pct range, short < long, burn > 0, known severity,
  unique names): exactly the checks `load_objectives` enforces at engine
  start, surfaced at review time instead of flight-recorded at run time.
* **inventory** — every referenced metric exists: against the
  `tools/metricsdump` known-names inventory by default, against a live
  telemetry plane with ``--live HOST:PORT`` (scrapes ``/metrics``), or
  against a dumped Prometheus text file with ``--prom FILE``.

Usage::

    python -m tools.slocheck objectives.toml
    python -m tools.slocheck objectives.json --live 127.0.0.1:9100
    python -m tools.slocheck objectives.toml --prom metrics.prom
    python -m tools.slocheck --selfcheck      # rides tier-1

``--selfcheck`` validates the engine's shipped default objectives against
the inventory (so a default referencing a renamed metric fails CI) and
asserts that a deliberately broken file is rejected with a useful
diagnostic.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import urllib.request

_BAD_FILE = """\
[[slo]]
name = "broken"
metric = "serve.no_such_metric"
op = "!="
threshold = 1.0
objective_pct = 150.0
windows = [ { short_secs = 3600, long_secs = 300, burn = -1, severity = "sms" } ]
"""


def _prom_base_names(text: str) -> set:
    """Metric base names present in a Prometheus text exposition, with the
    histogram _bucket/_sum/_count expansion folded back."""
    from paddle_tpu.utils.monitor import parse_prometheus_text

    names = set()
    for (name, _labels) in parse_prometheus_text(text):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
                break
        names.add(name)
    return names


def check_file(path: str, prom_names: set = None) -> list:
    """Problems with one objective file as (subject, problem) pairs.
    ``prom_names`` switches the inventory to a Prometheus name set (live
    scrape or dump); default is the metricsdump known-names inventory."""
    from paddle_tpu.utils import slo as _slo

    try:
        objectives = _slo.load_objectives(path)
    except OSError as e:
        return [(path, f"cannot read: {e}")]
    except ValueError as e:
        return [(path, f"invalid: {e}")]
    problems = []
    for s in objectives:
        if prom_names is not None:
            # prometheus renders dots as underscores
            if s.metric.replace(".", "_") not in prom_names:
                problems.append(
                    (s.metric, f"SLO {s.name!r}: metric not present in the "
                               "scraped/dumped exposition"))
        else:
            from tools.metricsdump import _KNOWN_NAMES
            if s.metric not in _KNOWN_NAMES and not s.metric.startswith("t."):
                problems.append(
                    (s.metric, f"SLO {s.name!r}: metric not in the "
                               "metricsdump known-names inventory"))
    return problems


def selfcheck() -> int:
    """Shipped defaults validate clean; a seeded-bad file is rejected."""
    from paddle_tpu.utils import slo as _slo
    from tools.metricsdump import _KNOWN_NAMES

    failures = []
    for s in _slo.default_objectives():
        if s.metric not in _KNOWN_NAMES:
            failures.append(f"default objective {s.name!r} references "
                            f"unknown metric {s.metric!r}")
    # default windows must be well-formed SRE pairs
    for w in _slo.DEFAULT_WINDOWS:
        if not (w.short_secs < w.long_secs and w.burn > 0):
            failures.append(f"default window {w!r} is malformed")
    # a deliberately broken file must be rejected at parse/validate time
    fd, bad_path = tempfile.mkstemp(suffix=".toml", prefix="slocheck_bad_")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(_BAD_FILE)
        if not check_file(bad_path):
            failures.append("seeded-bad objective file validated clean "
                            "(the validator is not checking)")
    finally:
        os.unlink(bad_path)
    # and the round trip: defaults serialize -> parse -> same objectives
    doc = {"slo": [s.to_json() for s in _slo.default_objectives()]}
    parsed = _slo.parse_objectives(doc)
    if [s.name for s in parsed] != [s.name
                                    for s in _slo.default_objectives()]:
        failures.append("default objectives do not round-trip through "
                        "parse_objectives")
    for f_ in failures:
        print(f"slocheck: FAIL: {f_}", file=sys.stderr)
    if failures:
        return 1
    print(f"slocheck: selfcheck OK ({len(_slo.default_objectives())} "
          "default objectives)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.slocheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("file", nargs="?", default=None,
                        help="objective file (TOML or JSON) to validate")
    parser.add_argument("--live", default=None, metavar="HOST:PORT",
                        help="validate metric names against a live "
                        "telemetry plane's /metrics instead of the "
                        "static inventory")
    parser.add_argument("--prom", default=None, metavar="FILE",
                        help="validate metric names against a dumped "
                        "Prometheus text file (metricsdump --out)")
    parser.add_argument("--selfcheck", action="store_true",
                        help="validate the shipped default objectives and "
                        "the validator itself (CI mode)")
    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()
    if args.file is None:
        parser.error("an objective file (or --selfcheck) is required")

    prom_names = None
    if args.live:
        try:
            with urllib.request.urlopen(
                    f"http://{args.live}/metrics", timeout=5.0) as r:
                prom_names = _prom_base_names(r.read().decode("utf-8"))
        except OSError as e:
            print(f"slocheck: cannot scrape {args.live}: {e}",
                  file=sys.stderr)
            return 2
    elif args.prom:
        try:
            with open(args.prom, "r", encoding="utf-8") as f:
                prom_names = _prom_base_names(f.read())
        except OSError as e:
            print(f"slocheck: cannot read {args.prom}: {e}", file=sys.stderr)
            return 2

    problems = check_file(args.file, prom_names)
    for subject, problem in problems:
        print(f"slocheck: {subject}: {problem}", file=sys.stderr)
    if problems:
        return 1
    from paddle_tpu.utils import slo as _slo
    n = len(_slo.load_objectives(args.file))
    print(f"slocheck: {args.file}: {n} objectives OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
