"""Op-level cost attribution + roofline/MFU + device-memory report CLI.

The TPU-native answer to the reference's tools/timeline.py over CUPTI
device-tracer protos (platform/device_tracer.h): instead of joining kernel
timestamps to ops after the fact, the Executor plants per-op
``jax.named_scope`` markers at trace time, and ``paddle_tpu/utils/xprof.py``
joins XLA's own cost/memory model back to those source ops from the
optimized HLO of the artifact that actually runs.

Usage::

    python -m tools.xprof                        # toy fc model, table view
    python -m tools.xprof --model mlp --steps 8 --batch 64 --hidden 256
    python -m tools.xprof --format json --out report.json
    python -m tools.xprof --format chrome --out trace.json   # chrome://tracing
    python -m tools.xprof --input report.json --top 5        # re-render a dump
    python -m tools.xprof --selfcheck            # CI assertion mode (tier-1)

The toy models are stepbench-shaped (fc regression / deeper mlp) and run a
few measured steps first, so the report's MFU and modeled-vs-measured drift
are anchored by the real ``executor.step_time_ms`` median — on CPU CI the
absolute MFU is meaningless (fallback peaks), but attribution coverage,
compute/memory classification, and the ranked region list are exactly what
a TPU run produces.

``--selfcheck`` asserts the acceptance contract: attribution coverage
>= 90% of modeled flops on the toy model, every region carries a roofline
class + MFU, the memory breakdown sums match ``memory_analysis()``, a
synthetic compute-bound/memory-bound pair classifies correctly, and all
three render formats produce output.  Exits non-zero on any violation.
"""
from __future__ import annotations

import argparse
import json
import sys


def _ensure_cpu_devices() -> None:
    """Default JAX to CPU when no flag is set, mirroring stepbench: the
    tool must run on a build box without TPUs attached."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_toy(model: str, batch: int, hidden: int):
    """A stepbench-style toy training program: (main, startup, loss, feeds)."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [hidden // 2])
        y = L.data("y", [1])
        h = L.fc(x, hidden, act="relu")
        if model == "mlp":
            h = L.fc(h, hidden, act="relu")
            h = L.fc(h, hidden // 2, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.default_rng(0)
    feeds = {
        "x": rng.normal(size=(batch, hidden // 2)).astype(np.float32),
        "y": rng.normal(size=(batch, 1)).astype(np.float32),
    }
    return main, startup, loss, feeds


def run_and_profile(model: str = "fc", steps: int = 4, batch: int = 32,
                    hidden: int = 128, top=None):
    """Build the toy model, run ``steps`` measured Executor steps (metrics
    on, so step_time_ms anchors the report), and return the xprof report."""
    import paddle_tpu.static as static
    from paddle_tpu.core import flags as _flags

    _flags.set_flags({"metrics": True})
    main, startup, loss, feeds = build_toy(model, batch, hidden)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(max(2, steps)):
            exe.run(main, feed=feeds, fetch_list=[loss])
    return exe.xprof_report(main, top=top), exe


def render(report: dict, fmt: str, top: int) -> str:
    from paddle_tpu.utils import xprof

    if fmt == "json":
        return json.dumps(report, indent=2, sort_keys=True)
    if fmt == "chrome":
        return json.dumps(xprof.to_chrome_trace(report))
    return xprof.render_table(report, top=top)


def selfcheck() -> int:
    """Assert the xprof acceptance contract end to end; 0 on success."""
    from paddle_tpu.utils import xprof

    failures = []

    def check(cond: bool, what: str) -> None:
        (failures.append(what) if not cond else None)

    # 1) attribution on the toy model: >= 90% of modeled flops land on
    #    named source ops, every region is classified, MFU present
    report, exe = run_and_profile(model="fc", steps=4)
    t = report["totals"]
    check(t["attribution_coverage"] >= 0.9,
          f"attribution coverage {t['attribution_coverage']} < 0.9")
    check(t["flops_modeled"] > 0, "no modeled flops")
    check(t["measured_ms"] is not None and t["measured_ms"] > 0,
          "no measured step time anchored the report")
    check(t["mfu_measured"] is not None and t["mfu_measured"] >= 0,
          "no measured MFU")
    for row in report["regions"]:
        check(row["bound"] in ("compute", "memory"),
              f"region {row['region']} unclassified")
        check(row["mfu"] >= 0, f"region {row['region']} has no MFU")
    named = [r for r in report["regions"]
             if xprof.OP_SCOPE_RE.match(r["region"])]
    check(len(named) >= 3, f"only {len(named)} op-scope regions survived")

    # 2) the memory breakdown is internally consistent and matches the
    #    executable's memory_analysis() via Executor.memory_stats()
    mem = report.get("memory")
    check(bool(mem), "report has no memory block")
    if mem:
        check(mem["total_bytes"] == mem["args_bytes"] + mem["out_bytes"]
              + mem["temp_bytes"] + mem["code_bytes"],
              "memory breakdown does not sum to total")
        agg = exe.memory_stats()
        check(agg["programs"] >= 1, "Executor.memory_stats saw no entries")
        check(agg["total_bytes"] >= mem["total_bytes"],
              "Executor.memory_stats lost the profiled entry's bytes")

    # 3) telemetry rode along: coverage/MFU gauges + report counter (checked
    #    before the synthetic profiles below overwrite the last-report
    #    gauges with their scope-less coverage)
    from paddle_tpu.utils import monitor

    reg = monitor.default_registry()
    check(reg.get("xprof.reports").value() >= 1, "xprof.reports never inc'd")
    check(reg.get("xprof.attribution_coverage").value() >= 0.9,
          "xprof.attribution_coverage gauge not set")

    # 4) roofline classification: a big matmul is compute-bound, an
    #    elementwise add is memory-bound (ridge holds on every peak table
    #    entry, CPU fallback included)
    import jax.numpy as jnp
    import numpy as np

    a = np.zeros((512, 512), np.float32)
    cb = xprof.profile_jit(lambda p, q: p @ q, a, a)
    check(cb["regions"][0]["bound"] == "compute",
          f"512x512 matmul classified {cb['regions'][0]['bound']}")
    mb = xprof.profile_jit(lambda p, q: jnp.add(p, q), a, a)
    check(mb["regions"][0]["bound"] == "memory",
          f"elementwise add classified {mb['regions'][0]['bound']}")

    # 5) every render format produces non-empty output
    for fmt in ("table", "json", "chrome"):
        check(bool(render(report, fmt, top=5).strip()),
              f"{fmt} render came back empty")
    chrome = xprof.to_chrome_trace(report)
    check(len(chrome["traceEvents"]) > 1, "chrome trace has no events")

    if failures:
        for f in failures:
            print(f"xprof selfcheck FAIL: {f}", file=sys.stderr)
        return 1
    cov = report["totals"]["attribution_coverage"]
    print(f"xprof selfcheck: OK (coverage {cov:.1%}, "
          f"{len(report['regions'])} regions, "
          f"drift x{report['totals']['measured_vs_modeled']})")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.xprof", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--model", choices=("fc", "mlp"), default="fc",
                        help="toy program to profile (default: fc)")
    parser.add_argument("--steps", type=int, default=4,
                        help="measured Executor steps anchoring MFU")
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--format", choices=("table", "json", "chrome"),
                        default="table")
    parser.add_argument("--top", type=int, default=20,
                        help="regions shown in the table view")
    parser.add_argument("--out", default=None,
                        help="write to this file instead of stdout")
    parser.add_argument("--input", default=None,
                        help="re-render a saved JSON report instead of "
                        "running a model")
    parser.add_argument("--selfcheck", action="store_true",
                        help="assert the acceptance contract (CI mode)")
    args = parser.parse_args(argv)

    _ensure_cpu_devices()
    if args.selfcheck:
        return selfcheck()

    if args.input:
        with open(args.input) as f:
            report = json.load(f)
        if report.get("schema") != "xprof.report.v1":
            print(f"xprof: {args.input} is not an xprof report "
                  f"(schema {report.get('schema')!r})", file=sys.stderr)
            return 1
    else:
        report, _ = run_and_profile(args.model, args.steps, args.batch,
                                    args.hidden)

    text = render(report, args.format, args.top)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"xprof: wrote {args.format} report to {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
