"""YOLOv3 step decomposition (r05 ladder): fwd / fwd+loss / full device
time via fori_loop, plus a loss-only micro.  Run on the TPU."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import autograd
from paddle_tpu.autograd import parameters_dict
from paddle_tpu.optimizer import Momentum
from paddle_tpu.vision.models.yolov3 import yolov3_darknet53

PEAK = 197e12
BATCH, SIZE, NGT = 32, 416, 16
K = 10
FWD_FLOPS = 65.86e9 * BATCH


def main():
    model = yolov3_darknet53(num_classes=80)
    model.train()
    params = parameters_dict(model)
    opt = Momentum(learning_rate=1e-4, momentum=0.9)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((BATCH, 3, SIZE, SIZE)),
                         jnp.bfloat16)
    wh = rng.uniform(0.05, 0.4, (BATCH, NGT, 2))
    cxy = rng.uniform(0.2, 0.8, (BATCH, NGT, 2))
    gt_box = jnp.asarray(np.concatenate([cxy, wh], -1), jnp.float32)
    gt_label = jnp.asarray(rng.integers(0, 80, (BATCH, NGT)), jnp.int32)

    def cast(p):
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

    def heads_of(p, imgs):
        return autograd.functional_call(model, cast(p), (imgs,))

    def loss_of(p, imgs):
        heads = [h.astype(jnp.float32) for h in heads_of(p, imgs)]
        return model.loss(heads, gt_box, gt_label)

    def timed(jit_fn, x0):
        out = jit_fn(x0)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        jax.block_until_ready(jit_fn(x0))
        return (time.perf_counter() - t0) / K

    @jax.jit
    def fwd_loop(imgs):
        def body(i, im):
            heads = heads_of(params, im)
            s = sum(jnp.mean(h.astype(jnp.float32)) for h in heads)
            return im + (s * 1e-12).astype(im.dtype)
        return jax.lax.fori_loop(0, K, body, imgs)

    dt = timed(fwd_loop, images)
    print(json.dumps({"probe": "fwd", "ms": round(dt * 1e3, 2),
                      "mfu": round(FWD_FLOPS / dt / PEAK, 4)}))

    # loss-only: heads precomputed, loss recomputed per iteration
    heads_const = [h.astype(jnp.float32)
                   for h in heads_of(params, images)]

    @jax.jit
    def loss_loop(h0):
        def body(i, h):
            heads = [h] + heads_const[1:]
            loss = model.loss(heads, gt_box, gt_label)
            return h + (loss * 1e-12).astype(h.dtype)
        return jax.lax.fori_loop(0, K, body, h0)

    dt = timed(loss_loop, heads_const[0])
    print(json.dumps({"probe": "loss_only", "ms": round(dt * 1e3, 2)}))

    @jax.jit
    def fwdloss_loop(imgs):
        def body(i, im):
            return im + (loss_of(params, im) * 1e-12).astype(im.dtype)
        return jax.lax.fori_loop(0, K, body, imgs)

    dt = timed(fwdloss_loop, images)
    print(json.dumps({"probe": "fwd+loss", "ms": round(dt * 1e3, 2)}))

    @jax.jit
    def full_loop(imgs):
        def body(i, carry):
            p, s, _ = carry
            loss, grads = jax.value_and_grad(loss_of)(p, imgs)
            p, s = opt.update(grads, s, p)
            return p, s, loss
        return jax.lax.fori_loop(
            0, K, body, (params, opt_state, jnp.zeros(())))

    out = full_loop(images)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    jax.block_until_ready(full_loop(images))
    dt = (time.perf_counter() - t0) / K
    print(json.dumps({"probe": "full", "ms": round(dt * 1e3, 2),
                      "ips": round(BATCH / dt, 1),
                      "mfu": round(3 * FWD_FLOPS / dt / PEAK, 4)}))


if __name__ == "__main__":
    main()
