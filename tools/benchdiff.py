"""benchdiff — regression gate between two BENCH_*.json revisions.

The repo's benchmark ledger is a pile of per-revision JSON files in three
shapes (all produced by earlier PRs' bench tools):

* ``{"parsed": {"metric": ..., "value": ..., "unit": ...}}``       (stepbench)
* ``{"results": [{"metric": ..., "value": ..., "unit": ...}]}``    (vision)
* ``{"record": {...nested numeric scalars...}}``            (serve/collbench)

``benchdiff OLD NEW`` extracts every numeric metric from both, compares
them with a per-metric tolerance band, and prints ONE JSON line::

    {"verdict": "pass"|"fail", "compared": N, "regressions": [...],
     "improvements": [...], "only_old": [...], "only_new": [...]}

exit 0 on pass, 1 on fail — pipe it into CI as a gate.  Direction is
inferred per metric: throughput/qps/speedup/goodput/mfu (or any ``/sec``
unit) regress when they DROP; latency/``*_ms``/``p50``..``p99`` regress
when they RISE; anything unrecognized is two-sided (any move beyond
tolerance fails, so a renamed unit can't silently exempt a metric).

``--tolerance 0.10`` (default) is the relative band; ``--metric-tolerance
name=0.25`` (repeatable, substring match) widens noisy metrics without
loosening the rest.  Metrics present on only one side are reported but
don't fail the gate (``--require-common`` makes them fail).

``--selfcheck`` builds synthetic revisions in a temp dir and asserts the
gate passes on identical inputs and fails on a seeded 20% regression —
rides tier-1 (tests/test_telemetry.py) so the gate itself is gated.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

__all__ = ["extract_metrics", "diff_metrics", "direction_of", "main"]

_HIGHER_HINTS = ("throughput", "qps", "speedup", "goodput", "mfu",
                 "occupancy", "bandwidth", "flops", "samples", "tokens")
_LOWER_HINTS = ("latency", "_ms", "p50", "p95", "p99", "time", "wait",
                "ttft", "overhead")


def direction_of(metric: str, unit: str = "") -> str:
    """'higher' | 'lower' | 'both' — which way this metric regresses."""
    name = metric.lower()
    u = (unit or "").lower()
    if u and ("/sec" in u or u.endswith("/s")):
        return "higher"
    if any(h in name for h in _HIGHER_HINTS):
        return "higher"
    if any(h in name for h in _LOWER_HINTS) or u in ("ms", "s", "us"):
        return "lower"
    return "both"


def _flatten(prefix: str, node, out: Dict[str, float]) -> None:
    if isinstance(node, bool):
        return
    if isinstance(node, (int, float)):
        if math.isfinite(float(node)):
            out[prefix] = float(node)
        return
    if isinstance(node, dict):
        # histogram-shaped subtrees (bucket-bound keys) are not metrics
        if "buckets" in node and "count" in node:
            return
        for k, v in node.items():
            if str(k).startswith("_"):
                continue
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


def extract_metrics(path: str) -> Dict[str, Tuple[float, str]]:
    """{metric: (value, unit)} from any of the BENCH_*.json shapes."""
    with open(path) as f:
        doc = json.load(f)
    out: Dict[str, Tuple[float, str]] = {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object at top level")
    entries = []
    if isinstance(doc.get("parsed"), dict):
        entries.append(doc["parsed"])
    if isinstance(doc.get("results"), list):
        entries.extend(e for e in doc["results"] if isinstance(e, dict))
    for e in entries:
        name = e.get("metric")
        if name is None or not isinstance(e.get("value"), (int, float)):
            continue
        out[str(name)] = (float(e["value"]), str(e.get("unit", "")))
    if isinstance(doc.get("record"), dict):
        flat: Dict[str, float] = {}
        _flatten("", doc["record"], flat)
        for k, v in flat.items():
            out.setdefault(k, (v, ""))
    if not out:
        raise ValueError(
            f"{path}: no metrics found — expected 'parsed', 'results' or "
            "'record' (the stepbench/vision/servebench BENCH schemas)")
    return out


def _tolerance_for(metric: str, default: float,
                   overrides: List[Tuple[str, float]]) -> float:
    for pat, tol in overrides:
        if pat in metric:
            return tol
    return default


def diff_metrics(old: Dict[str, Tuple[float, str]],
                 new: Dict[str, Tuple[float, str]],
                 tolerance: float = 0.10,
                 overrides: Optional[List[Tuple[str, float]]] = None,
                 require_common: bool = False) -> Dict:
    overrides = overrides or []
    regressions, improvements, unchanged = [], [], 0
    common = sorted(set(old) & set(new))
    for m in common:
        (ov, unit), (nv, _) = old[m], new[m]
        tol = _tolerance_for(m, tolerance, overrides)
        denom = abs(ov) if ov else 1.0
        rel = (nv - ov) / denom
        direction = direction_of(m, unit)
        worse = (rel < -tol if direction == "higher"
                 else rel > tol if direction == "lower"
                 else abs(rel) > tol)
        better = (rel > tol if direction == "higher"
                  else rel < -tol if direction == "lower"
                  else False)
        entry = {"metric": m, "old": ov, "new": nv,
                 "change_pct": round(100.0 * rel, 2),
                 "direction": direction, "tolerance_pct": 100.0 * tol}
        if worse:
            regressions.append(entry)
        elif better:
            improvements.append(entry)
        else:
            unchanged += 1
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    fail = bool(regressions) or (require_common and (only_old or only_new))
    return {
        "verdict": "fail" if fail else "pass",
        "compared": len(common),
        "unchanged": unchanged,
        "regressions": regressions,
        "improvements": improvements,
        "only_old": only_old,
        "only_new": only_new,
    }


# ---------------------------------------------------------------------------
# selfcheck


def _write(path: str, doc: dict) -> str:
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def selfcheck() -> int:
    tmp = tempfile.mkdtemp(prefix="benchdiff_selfcheck_")
    base = {
        "parsed": {"metric": "train_throughput", "value": 1000.0,
                   "unit": "tokens/sec/chip"},
        "results": [
            {"metric": "infer_p99_ms", "value": 5.0, "unit": "ms"},
            {"metric": "train_mfu", "value": 0.5, "unit": ""},
        ],
        "record": {"batched": {"qps": 2000.0, "p50_ms": 1.5}},
    }
    a = _write(os.path.join(tmp, "a.json"), base)
    b = _write(os.path.join(tmp, "b.json"), base)
    same = diff_metrics(extract_metrics(a), extract_metrics(b))
    ok = same["verdict"] == "pass" and not same["regressions"]

    # seeded 20% regressions, one per direction class: throughput drops,
    # latency rises — both must trip a 10% band
    worse = json.loads(json.dumps(base))
    worse["parsed"]["value"] = 800.0
    worse["results"][0]["value"] = 6.0
    c = _write(os.path.join(tmp, "c.json"), worse)
    bad = diff_metrics(extract_metrics(a), extract_metrics(c))
    tripped = {e["metric"] for e in bad["regressions"]}
    ok = (ok and bad["verdict"] == "fail"
          and {"train_throughput", "infer_p99_ms"} <= tripped)

    # and the band actually tolerates sub-threshold noise
    noisy = json.loads(json.dumps(base))
    noisy["parsed"]["value"] = 950.0      # -5% < 10% band
    d = _write(os.path.join(tmp, "d.json"), noisy)
    near = diff_metrics(extract_metrics(a), extract_metrics(d))
    ok = ok and near["verdict"] == "pass"

    print(json.dumps({"selfcheck": "pass" if ok else "fail",
                      "identical": same["verdict"],
                      "seeded_regression": bad["verdict"],
                      "tripped": sorted(tripped),
                      "sub_threshold": near["verdict"]}))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.benchdiff",
        description="Regression gate between two BENCH_*.json revisions "
                    "(one JSON verdict line; exit 1 on regression)")
    p.add_argument("old", nargs="?", help="baseline BENCH_*.json")
    p.add_argument("new", nargs="?", help="candidate BENCH_*.json")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="relative tolerance band (default 0.10 = 10%%)")
    p.add_argument("--metric-tolerance", action="append", default=[],
                   metavar="SUBSTR=TOL",
                   help="per-metric override, substring match "
                        "(e.g. --metric-tolerance p99=0.25); repeatable")
    p.add_argument("--require-common", action="store_true",
                   help="fail when a metric exists on only one side")
    p.add_argument("--selfcheck", action="store_true",
                   help="verify the gate on synthetic revisions and exit")
    args = p.parse_args(argv)
    if args.selfcheck:
        return selfcheck()
    if not args.old or not args.new:
        p.error("old and new BENCH files are required (or --selfcheck)")
    overrides = []
    for spec in args.metric_tolerance:
        if "=" not in spec:
            p.error(f"--metric-tolerance wants SUBSTR=TOL, got {spec!r}")
        pat, tol = spec.rsplit("=", 1)
        overrides.append((pat, float(tol)))
    verdict = diff_metrics(extract_metrics(args.old),
                           extract_metrics(args.new),
                           tolerance=args.tolerance, overrides=overrides,
                           require_common=args.require_common)
    print(json.dumps(verdict))
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
