"""Recommender benchmark: wide&deep CTR training + serving over
vocab-sharded embeddings (parallel/embedding.py).

The ISSUE-15 acceptance harness as a tool: builds a synthetic wide&deep
CTR model (a wide ``(V, 1)`` linear table + a deep ``(V, D)`` embedding ->
slot-mean -> MLP, squashed through sigmoid + log loss), trains it on an
8-device CPU mesh with ``ShardingPlan(embedding_shard="tp")`` — every
lookup routed through the dedup + all_to_all exchange — and serves the
trained deep table through the multi-tenant frontend's embedding tenant
(submit-side id dedup).  Prints exactly ONE JSON line:

  * ``results`` — benchdiff-compatible rows ({metric, value, unit}):
    training rows/sec through the sharded path, the per-step per-device
    exchange-byte accounting (`embedding.exchange_bytes` over both
    tables, fp32 and int8-backward variants), serving qps and the
    observed submit-side unique-id ratio.
  * ``parity`` — the correctness gates, all booleans (benchdiff ignores
    them; ``--selfcheck`` enforces them): **token rows bitwise** (the
    deep embedding's forward output fetched from the sharded run equals
    the single-device dense reference bit-for-bit), every training-step
    loss within rtol 1e-6 of the dense
    reference (whole-step fusion reassociates fp32 sums at the last ulp —
    the lookup itself is bitwise, pinned by tests/test_sharded_embedding
    .py), **serving rows bitwise** against ``weight[ids]``, and zero
    steady-state retraces (``executor.traces`` flat across the timed
    loop).

On forced-host CPU devices the wall numbers measure dispatch, not TPU
compute — the exchange-byte accounting and the parity gates are the
portable numbers.

Usage:
    python -m tools.recbench [--devices N] [--vocab V] [--dim D]
                             [--slots S] [--batch B] [--steps K]
                             [--out BENCH_REC.json]
    python -m tools.recbench --selfcheck     # small sizes + gates; rides tier-1
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _ensure_cpu_devices(n: int) -> None:
    """Must run BEFORE jax imports: force enough virtual XLA host devices
    for an N-way mesh (no-op when a harness already exported XLA_FLAGS)."""
    if "jax" in sys.modules:
        return
    env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in env:
        os.environ["XLA_FLAGS"] = (
            env + f" --xla_force_host_platform_device_count={n}").strip()


def _build_ctr(vocab: int, dim: int, slots: int, lr: float):
    """The wide&deep program: returns (main, startup, loss, emb_out,
    deep_table_name)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = L.data("ids", [slots], dtype="int64")
        y = L.data("y", [1])
        deep = L.embedding(ids, size=[vocab, dim], name="deep_emb")
        wide = L.embedding(ids, size=[vocab, 1], name="wide_emb")
        concat = L.reshape(deep, (-1, slots * dim))
        hidden = L.fc(concat, max(16, dim), act="relu")
        deep_logit = L.fc(hidden, 1)
        wide_logit = L.fc(L.reshape(wide, (-1, slots)), 1)
        prob = L.sigmoid(L.elementwise_add(wide_logit, deep_logit))
        loss = L.mean(L.log_loss(prob, y))
        static.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss, deep, "deep_emb.w"


def _zipf_ids(rng, vocab: int, shape, a: float = 1.3):
    """Skewed id draw (popular items dominate — the CTR dedup payoff)."""
    import numpy as np

    z = rng.zipf(a, size=shape)
    return ((z - 1) % vocab).astype(np.int64)


def run_bench(args) -> dict:
    import numpy as np
    import jax
    from jax.sharding import Mesh

    import paddle_tpu.static as static
    from paddle_tpu.parallel import embedding as pemb
    from paddle_tpu.utils import monitor

    V, D, S, B = args.vocab, args.dim, args.slots, args.batch
    steps, k = args.steps, args.devices
    rng = np.random.default_rng(0)
    ids = _zipf_ids(rng, V, (B, S))
    yv = (rng.random(size=(B, 1)) < 0.3).astype(np.float32)

    # -- single-device dense reference ------------------------------------
    main, startup, loss, emb_out, wname = _build_ctr(V, D, S, args.lr)
    exe = static.Executor()
    sc = static.Scope()
    losses_ref, rows_ref = [], None
    with static.scope_guard(sc):
        exe.run(startup)
        init = {p.name: np.array(sc.find_var(p.name))
                for p in main.all_parameters()}
        for i in range(steps):
            outs = exe.run(main, feed={"ids": ids, "y": yv},
                           fetch_list=[loss, emb_out])
            losses_ref.append(np.array(outs[0]))
            if i == 0:
                rows_ref = np.array(outs[1])

    # -- the sharded run: blanket embedding_shard over the tp axis --------
    if len(jax.devices()) < k:
        raise SystemExit(f"need {k} devices, have {len(jax.devices())}")
    mesh = Mesh(np.asarray(jax.devices()[:k]).reshape(1, k), ("dp", "tp"))
    main2, startup2, loss2, emb_out2, _ = _build_ctr(V, D, S, args.lr)
    comp = static.CompiledProgram(main2).with_sharding(
        mesh=mesh, embedding_shard="tp")
    exe2 = static.Executor()
    sc2 = static.Scope()
    traces = monitor.default_registry().get("executor.traces")
    losses_sh, rows_sh = [], None
    with static.scope_guard(sc2):
        exe2.run(startup2)
        for p1, p2 in zip(main.all_parameters(), main2.all_parameters()):
            sc2.set(p2.name, init[p1.name])
        # warmup (compiles) + token-row fetch for the parity gate
        outs = exe2.run(comp, feed={"ids": ids, "y": yv},
                        fetch_list=[loss2, emb_out2])
        losses_sh.append(np.array(outs[0]))
        rows_sh = np.array(outs[1])
        traces_warm = traces.value()
        t0 = time.perf_counter()
        for _ in range(steps - 1):
            losses_sh.append(np.array(exe2.run(
                comp, feed={"ids": ids, "y": yv},
                fetch_list=[loss2, emb_out2])[0]))
        dt = time.perf_counter() - t0
        retraces = traces.value() - traces_warm
        trained_w = np.asarray(sc2.find_var(wname), np.float32)
    rows_per_sec = B * max(1, steps - 1) / max(dt, 1e-9)

    # -- wire accounting: both covered tables, fp32 + int8 backward -------
    n_ids = B * S
    xbytes = (pemb.exchange_bytes(n_ids, D, k)
              + pemb.exchange_bytes(n_ids, 1, k))
    xbytes_q = (pemb.exchange_bytes(n_ids, D, k, quantize="int8")
                + pemb.exchange_bytes(n_ids, 1, k, quantize="int8"))

    # -- serving: embedding tenant + submit-side dedup --------------------
    from paddle_tpu.serving.frontend import Server

    req_ids = _zipf_ids(rng, V, (args.serve_rows,))
    n_req, qps, unique_ratio = 64, 0.0, 1.0
    with Server(bucket_edges=(args.serve_rows,), max_wait_ms=0.5) as srv:
        srv.add_embedding_tenant("ctr", trained_w)
        srv.submit("ctr", {"ids": req_ids}).result(timeout=60)  # warm
        t0 = time.perf_counter()
        futs = [srv.submit("ctr", {"ids": req_ids}) for _ in range(n_req)]
        outs = [f.result(timeout=60) for f in futs]
        qps = n_req / max(time.perf_counter() - t0, 1e-9)
        served = np.asarray(outs[-1][0], np.float32)
    g = monitor.default_registry().get("emb.unique_ratio")
    if g is not None:
        unique_ratio = float(g.value())
    serve_bitwise = bool(np.array_equal(served, trained_w[req_ids]))

    losses_ref_f = [float(x) for x in losses_ref]
    losses_sh_f = [float(x) for x in losses_sh]
    parity = {
        "token_rows_bitwise": bool(np.array_equal(rows_ref, rows_sh)),
        "losses_allclose_rtol1e6": bool(np.allclose(
            losses_ref_f, losses_sh_f, rtol=1e-6, atol=0.0)),
        "serve_rows_bitwise": serve_bitwise,
        "zero_steady_state_retraces": bool(retraces == 0),
    }
    results = [
        {"metric": "rec_train_throughput", "value": round(rows_per_sec, 1),
         "unit": "rows/sec", "devices": k, "batch": B, "slots": S},
        {"metric": "rec_exchange_bytes_per_step", "value": xbytes,
         "unit": "bytes/device", "tables": 2, "quantize": "none"},
        {"metric": "rec_exchange_bytes_per_step_int8", "value": xbytes_q,
         "unit": "bytes/device", "tables": 2, "quantize": "int8"},
        {"metric": "rec_serve_qps", "value": round(qps, 1),
         "unit": "req/sec", "rows": args.serve_rows},
        {"metric": "rec_serve_unique_ratio", "value": round(unique_ratio, 4),
         "unit": "ratio"},
    ]
    return {
        "_note": "recbench on XLA:CPU host devices — wall-clock rows/sec "
                 "and qps measure host dispatch, not TPU compute; the "
                 "exchange-byte accounting and the parity booleans are the "
                 "portable numbers.",
        "command": "python -m tools.recbench --out BENCH_REC.json",
        "bench": "recbench", "schema": 1, "environment": "cpu",
        "devices": k, "vocab": V, "dim": D, "slots": S, "batch": B,
        "steps": steps, "results": results, "parity": parity,
        "losses": {"ref": losses_ref_f, "sharded": losses_sh_f},
    }


def _selfcheck(result) -> int:
    """Acceptance gates (ISSUE 15): schema, every parity bool true,
    quantized wire strictly below fp32, positive throughput."""
    errors = []
    for field in ("results", "parity", "losses", "devices"):
        if field not in result:
            errors.append(f"missing field {field!r}")
    for name, ok in result.get("parity", {}).items():
        if not ok:
            errors.append(f"parity gate {name} failed")
    by_metric = {r["metric"]: r["value"] for r in result.get("results", ())}
    if not by_metric.get("rec_train_throughput", 0) > 0:
        errors.append("non-positive training throughput")
    if not by_metric.get("rec_serve_qps", 0) > 0:
        errors.append("non-positive serving qps")
    if not (0 < by_metric.get("rec_exchange_bytes_per_step_int8", 0)
            < by_metric.get("rec_exchange_bytes_per_step", 0)):
        errors.append("int8 exchange accounting not below fp32")
    if not by_metric.get("rec_serve_unique_ratio", 1.0) < 1.0:
        errors.append("zipf request batch deduplicated nothing")
    if errors:
        print("SELFCHECK FAIL:", "; ".join(errors), file=sys.stderr)
        return 1
    print("recbench selfcheck: OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="recbench", description=__doc__)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--serve-rows", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--out", default=None,
                   help="also write the JSON to this file")
    p.add_argument("--selfcheck", action="store_true",
                   help="small sizes + acceptance gates; exit 0/1")
    args = p.parse_args(argv)
    _ensure_cpu_devices(args.devices)
    if args.selfcheck:
        args.vocab, args.dim, args.slots = 64, 8, 4
        args.batch, args.steps, args.serve_rows = 32, 6, 64
    result = run_bench(args)
    text = json.dumps(result)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, sort_keys=False)
            f.write("\n")
    if args.selfcheck:
        return _selfcheck(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
