"""Flash-attention head_dim-64 MXU-rate probe (r05, VERDICT item 5).

Measures the packed kernel's achieved matmul rate at the ERNIE flagship
shape against the d=64 STRUCTURAL ceiling (contraction/output dim 64 =
half the 128-lane MXU -> 98.5 TFLOP/s), with a block-size sweep.
fori_loop-chained (the only valid micro over the axon tunnel).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas import flash_attention_packed as fp

PEAK = 197e12
HALF = PEAK / 2
B, S, H, D = 64, 512, 12, 64
ITERS = 20


def timed(fn, x0, iters=ITERS):
    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, iters, fn, x)

    jax.block_until_ready(run(x0))
    t0 = time.perf_counter()
    jax.block_until_ready(run(x0))
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.standard_normal((B, S, H * D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, S, H * D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, S, H * D)), jnp.bfloat16)
    fwd_flops = 4 * B * H * S * S * D

    for bq, bk in [(512, 512), (256, 256)]:
        def body(i, q, bq=bq, bk=bk):
            o = fp.flash_attention_packed(q, k, v, num_heads=H,
                                          block_q=bq, block_k=bk)
            return q + (jnp.mean(o.astype(jnp.float32)) * 1e-12).astype(
                q.dtype)

        dt = timed(body, q0)
        print(json.dumps({
            "probe": f"packed_fwd_bq{bq}_bk{bk}",
            "ms": round(dt * 1e3, 3),
            "pct_of_half_peak": round(fwd_flops / dt / HALF * 100, 1)}))

    # fwd+bwd at the default blocks
    dy = jnp.asarray(rng.standard_normal((B, S, H * D)), jnp.bfloat16)

    def fb(i, q):
        def f(q_):
            return jnp.sum(fp.flash_attention_packed(
                q_, k, v, num_heads=H).astype(jnp.float32) * dy.astype(
                jnp.float32))
        g = jax.grad(f)(q)
        return q + (g * 1e-12).astype(q.dtype)

    dt = timed(fb, q0)
    total_flops = fwd_flops * 3.5  # fwd + dkdv + dq kernel passes
    print(json.dumps({"probe": "packed_fwdbwd_default",
                      "ms": round(dt * 1e3, 3),
                      "pct_of_half_peak":
                      round(total_flops / dt / HALF * 100, 1)}))


if __name__ == "__main__":
    main()
