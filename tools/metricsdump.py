"""Run a small static-graph workload and dump the runtime metric registry.

The observability analogue of proglint: a one-command answer to "is the
telemetry layer wired up, and what does it report?"  Builds a tiny fc
regression program, runs the Executor a few steps (one compile + N cached
runs), then prints the process-wide `MetricRegistry` as Prometheus text or
JSON — so `executor.cache_miss/.cache_hit`, the compile/run histograms,
`registry.lowering_calls{op=...}` and friends are all populated.

Usage::

    python -m tools.metricsdump                    # prometheus text
    python -m tools.metricsdump --format json
    python -m tools.metricsdump --steps 10 --out metrics.prom
    python -m tools.metricsdump --chrome trace.json   # spans + counter track
    python -m tools.metricsdump --lint             # metric-name lint only

`--lint` checks every registered metric name against ``^[a-z0-9_.]+$``
(the registry enforces this at registration; the lint is the CI backstop
that keeps exporter output Prometheus-legal) and exits non-zero on any
violation.
"""
from __future__ import annotations

import argparse
import json
import re
import sys

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")


def run_workload(steps: int = 3) -> None:
    """One compile + (steps - 1) cached Executor runs of a tiny fc model."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L
    from paddle_tpu.utils import profiler

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [8])
        y = L.data("y", [1])
        hidden = L.fc(x, 16, act="relu")
        pred = L.fc(hidden, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.default_rng(0)
    xv = rng.normal(size=(16, 8)).astype(np.float32)
    yv = rng.normal(size=(16, 1)).astype(np.float32)

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(max(1, steps)):
            with profiler.RecordEvent("metricsdump::step"):
                exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])


def _register_instrumented_modules() -> None:
    """Import every instrumented layer so its metrics are registered even
    when the workload doesn't exercise it (PS server, hapi loop)."""
    import paddle_tpu.distributed.ps_server  # noqa: F401
    import paddle_tpu.static.executor  # noqa: F401 — executor.* + registry.*
    from paddle_tpu.hapi.callbacks import MetricsLogger

    MetricsLogger()  # registers the train.* family


def lint_names(registry) -> list:
    return [n for n in registry.names() if not _NAME_RE.match(n)]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.metricsdump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--format", choices=("prom", "json"), default="prom",
                        help="export format (default: prometheus text)")
    parser.add_argument("--steps", type=int, default=3,
                        help="Executor.run steps (first one compiles)")
    parser.add_argument("--out", default=None,
                        help="write to this file instead of stdout")
    parser.add_argument("--chrome", default=None,
                        help="also export a chrome trace (spans + counter "
                        "track) to this path")
    parser.add_argument("--lint", action="store_true",
                        help="lint registered metric names instead of "
                        "running the workload dump")
    args = parser.parse_args(argv)

    from paddle_tpu.utils import monitor, profiler

    registry = monitor.default_registry()
    _register_instrumented_modules()

    if args.lint:
        bad = lint_names(registry)
        if bad:
            for name in bad:
                print(f"metricsdump: illegal metric name {name!r} "
                      f"(must match {_NAME_RE.pattern})", file=sys.stderr)
            return 1
        print(f"metricsdump: {len(registry.names())} metric names OK")
        return 0

    profiler.start_profiler()
    run_workload(args.steps)
    if args.chrome:
        profiler.export_chrome_tracing(args.chrome)
    # event summary goes to stderr so stdout stays pure prom/json payload
    profiler.stop_profiler(sorted_key="total", stream=sys.stderr)

    if args.format == "json":
        text = json.dumps(registry.to_json(), indent=2, sort_keys=True)
    else:
        text = registry.to_prometheus_text()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
