"""Run a small static-graph workload and dump the runtime metric registry.

The observability analogue of proglint: a one-command answer to "is the
telemetry layer wired up, and what does it report?"  Builds a tiny fc
regression program, runs the Executor a few steps (one compile + N cached
runs), then prints the process-wide `MetricRegistry` as Prometheus text or
JSON — so `executor.cache_miss/.cache_hit`, the compile/run histograms,
`registry.lowering_calls{op=...}` and friends are all populated.

Usage::

    python -m tools.metricsdump                    # prometheus text
    python -m tools.metricsdump --format json
    python -m tools.metricsdump --steps 10 --out metrics.prom
    python -m tools.metricsdump --chrome trace.json   # spans + counter track
    python -m tools.metricsdump --lint             # metric-name lint only

`--lint` checks every registered metric name against ``^[a-z0-9_.]+$``
(the registry enforces this at registration; the lint is the CI backstop
that keeps exporter output Prometheus-legal), then against the KNOWN-NAMES
inventory below — dashboards and alerts key on these exact strings, so a
new instrumented module must add its names here (the lint failing is the
review prompt) and a typo'd registration fails instead of silently
splitting a time series.  Exits non-zero on any violation.
"""
from __future__ import annotations

import argparse
import json
import re
import sys

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")

# The metric-name inventory: every name any instrumented module registers.
# Grouped by family; keep sorted within each group.
_KNOWN_NAMES = frozenset({
    # static/analysis.py + static/shardcheck.py + static/memcheck.py
    # (the three-tier verifier)
    "analysis.mem_checks",
    "analysis.mem_violations",
    "analysis.plans_checked",
    "analysis.programs_checked",
    "analysis.violations",
    # parallel/autoplan.py (plan-search telemetry)
    "autoplan.candidates",
    "autoplan.replans",
    "autoplan.search_ms",
    "autoplan.searches",
    "debug.nan_events",
    # parallel/collective.py + parallel/compress.py
    "comm.allreduce_bytes",
    "comm.allreduce_ms",
    "comm.compress_ratio",
    # parallel/embedding.py (vocab-sharded embedding exchange + serving)
    "emb.exchange_bytes",
    "emb.lookup_ms",
    "emb.unique_ratio",
    # elastic/ (checkpoint.py, membership.py, failover.py)
    "elastic.checkpoint_ms",
    "elastic.failovers",
    "elastic.resharded_leaves",
    "elastic.restore_ms",
    "elastic.worker_deaths",
    # static/executor.py + static/compile_cache.py
    "executor.cache_hit",
    "executor.cache_miss",
    "executor.cold_start_ms",
    "executor.compile_cache_hit",
    "executor.compile_cache_miss",
    "executor.compile_time_ms",
    "executor.cost_bytes_accessed",
    "executor.cost_flops",
    "executor.device_mem_args_bytes",
    "executor.device_mem_code_bytes",
    "executor.device_mem_live_arrays",
    "executor.device_mem_live_bytes",
    "executor.device_mem_out_bytes",
    "executor.device_mem_temp_bytes",
    "executor.device_mem_total_bytes",
    "executor.dispatch_time_ms",
    "executor.donated_bytes",
    "executor.predicted_peak_bytes",
    "executor.program_ops",
    "executor.state_size_bytes",
    "executor.step_time_ms",
    "executor.traces",
    # tools/fleetview.py (the job-level aggregator's own instruments)
    "fleet.ranks",
    "fleet.scrape_errors",
    "fleet.scrapes",
    # io/prefetch.py
    "io.prefetch_batches",
    "io.prefetch_depth",
    # utils/ledger.py (measured-vs-predicted calibration)
    "ledger.drift_alarms",
    "ledger.drift_ratio",
    "ledger.records",
    # ops/pallas/config.py (kernel dispatch telemetry)
    "pallas.fallbacks",
    "pallas.kernel_calls",
    # static/passes.py (graph-rewrite pipeline)
    "passes.ops_fused",
    "passes.ops_removed",
    "passes.pipeline_ms",
    "passes.rollbacks",
    "passes.runs",
    # static/passes.py quant_infer (int8 inference rewrite)
    "quant.ops_rewritten",
    # distributed/ps_server.py
    "ps.heartbeat_age_seconds",
    "ps.rpc_count",
    "ps.rpc_errors",
    "ps.rpc_latency_ms",
    "registry.lowering_calls",
    # serving/ (slo.py, tenancy.py, continuous.py, paged.py)
    "serve.batch_occupancy",
    "serve.batch_size",
    "serve.decode_active_slots",
    "serve.kv_blocks_free",
    "serve.kv_cache_bytes",
    "serve.kv_prefill_chunks",
    "serve.kv_prefix_hits",
    "serve.live_programs",
    "serve.live_temp_bytes",
    "serve.load_shed",
    "serve.peak_temp_bytes",
    "serve.program_evictions",
    "serve.projected_p99_ms",
    "serve.queue_depth",
    "serve.request_ms",
    "serve.requests",
    "serve.ttft_batch_ms",
    "serve.ttft_compile_ms",
    "serve.ttft_execute_ms",
    "serve.ttft_ms",
    "serve.ttft_p50_ms",
    "serve.ttft_p99_ms",
    "serve.ttft_queue_ms",
    # utils/slo.py (the SLO engine's own instruments)
    "slo.alerts_firing",
    "slo.burn_rate",
    "slo.evaluations",
    # utils/telemetry.py (the HTTP exposition plane)
    "telemetry.port",
    "telemetry.requests",
    "telemetry.scrape_ms",
    # hapi/callbacks.py MetricsLogger + utils/watchdog.py goodput
    "train.epochs",
    "train.goodput_pct",
    "train.samples_per_sec",
    "train.step_time_ms",
    "train.steps",
    # utils/watchdog.py (anomaly detection)
    "watchdog.anomalies",
    "watchdog.checkpoints",
    "watchdog.time_ms",
    # utils/xprof.py
    "xprof.attribution_coverage",
    "xprof.mfu",
    "xprof.reports",
})


def run_workload(steps: int = 3) -> None:
    """One compile + (steps - 1) cached Executor runs of a tiny fc model."""
    import numpy as np

    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L
    from paddle_tpu.utils import profiler

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [8])
        y = L.data("y", [1])
        hidden = L.fc(x, 16, act="relu")
        pred = L.fc(hidden, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.default_rng(0)
    xv = rng.normal(size=(16, 8)).astype(np.float32)
    yv = rng.normal(size=(16, 1)).astype(np.float32)

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(max(1, steps)):
            with profiler.RecordEvent("metricsdump::step"):
                exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])


def _register_instrumented_modules() -> None:
    """Import every instrumented layer so its metrics are registered even
    when the workload doesn't exercise it (PS server, hapi loop)."""
    import paddle_tpu.distributed.ps_server  # noqa: F401
    import paddle_tpu.elastic  # noqa: F401 — the elastic.* family
    import paddle_tpu.parallel.autoplan  # noqa: F401 — the autoplan.* family
    import paddle_tpu.parallel.embedding  # noqa: F401 — the emb.* family
    import paddle_tpu.serving  # noqa: F401 — the serve.* family
    import paddle_tpu.static.analysis  # noqa: F401 — analysis.* counters
    import paddle_tpu.static.shardcheck  # noqa: F401 — analysis.plans_checked
    import paddle_tpu.static.compile_cache  # noqa: F401
    import paddle_tpu.static.executor  # noqa: F401 — executor.* + registry.*
    import paddle_tpu.ops.pallas.config  # noqa: F401 — the pallas.* family
    import paddle_tpu.static.passes  # noqa: F401 — passes.* + quant.*
    import paddle_tpu.utils.debug  # noqa: F401
    import paddle_tpu.utils.ledger  # noqa: F401 — the ledger.* family
    import paddle_tpu.utils.slo  # noqa: F401 — the slo.* family
    import paddle_tpu.utils.telemetry  # noqa: F401 — the telemetry.* family
    import paddle_tpu.utils.watchdog  # noqa: F401 — watchdog.* + goodput
    import paddle_tpu.utils.xprof  # noqa: F401 — the xprof.* family
    import tools.fleetview  # noqa: F401 — the fleet.* family
    from paddle_tpu.hapi.callbacks import MetricsLogger

    MetricsLogger()  # registers the train.* family


def lint_names(registry) -> list:
    """(name, problem) pairs: names the exporters would reject or that are
    missing from the _KNOWN_NAMES inventory."""
    bad = []
    for n in registry.names():
        if not _NAME_RE.match(n):
            bad.append((n, f"must match {_NAME_RE.pattern}"))
        elif n not in _KNOWN_NAMES and not n.startswith("t."):
            # "t." is the reserved scratch namespace (tests, ad-hoc probes)
            bad.append((n, "not in the metricsdump known-names inventory; "
                           "add it to _KNOWN_NAMES"))
    return bad


def lint_objectives(path: str) -> list:
    """(name, problem) pairs for an SLO objective file: parse failures and
    objectives whose metric is missing from the known-names inventory —
    an alert rule keying on a metric nothing registers would silently
    never fire."""
    from paddle_tpu.utils import slo as _slo

    try:
        objectives = _slo.load_objectives(path)
    except (OSError, ValueError) as e:
        return [(path, f"objective file failed to load: {e}")]
    bad = []
    for s in objectives:
        if s.metric not in _KNOWN_NAMES and not s.metric.startswith("t."):
            bad.append((s.metric,
                        f"SLO {s.name!r} references a metric not in the "
                        "metricsdump known-names inventory"))
    return bad


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.metricsdump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--format", choices=("prom", "json"), default="prom",
                        help="export format (default: prometheus text)")
    parser.add_argument("--steps", type=int, default=3,
                        help="Executor.run steps (first one compiles)")
    parser.add_argument("--out", default=None,
                        help="write to this file instead of stdout")
    parser.add_argument("--chrome", default=None,
                        help="also export a chrome trace (spans + counter "
                        "track) to this path")
    parser.add_argument("--lint", action="store_true",
                        help="lint registered metric names instead of "
                        "running the workload dump")
    parser.add_argument("--objectives", default=None, metavar="FILE",
                        help="with --lint: also validate this SLO objective "
                        "file (utils/slo.py format) — fails on objectives "
                        "referencing metrics missing from the inventory")
    args = parser.parse_args(argv)

    from paddle_tpu.utils import monitor, profiler

    registry = monitor.default_registry()
    _register_instrumented_modules()

    if args.lint:
        bad = lint_names(registry)
        if args.objectives:
            bad.extend(lint_objectives(args.objectives))
        if bad:
            for name, problem in bad:
                print(f"metricsdump: bad metric name {name!r}: {problem}",
                      file=sys.stderr)
            return 1
        print(f"metricsdump: {len(registry.names())} metric names OK"
              + (f" (+ objectives {args.objectives} OK)"
                 if args.objectives else ""))
        return 0

    profiler.start_profiler()
    run_workload(args.steps)
    if args.chrome:
        profiler.export_chrome_tracing(args.chrome)
    # event summary goes to stderr so stdout stays pure prom/json payload
    profiler.stop_profiler(sorted_key="total", stream=sys.stderr)

    if args.format == "json":
        text = json.dumps(registry.to_json(), indent=2, sort_keys=True)
    else:
        text = registry.to_prometheus_text()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
