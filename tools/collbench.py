"""Collective-communication benchmark: gradient allreduce GB/s and
end-to-end training throughput for {none, int8, fp8} payloads x
{flat, hierarchical} schedules (parallel/compress.py).

Prints exactly ONE JSON line:

  * ``configs`` — per (compress, schedule) pair: median wall ms of one
    allreduce of ``--mb`` MB of fp32 gradients, achieved wire GB/s, the
    wire-byte accounting (`compress.wire_bytes`: 2*(n-1)/n * payload, where
    a quantized payload is 1 byte/element + one fp32 scale per block) and
    its ratio to the fp32 baseline.  On forced-host CPU devices the wall
    times measure scheduling, not ICI — the wire accounting is the
    portable number (cost_analysis does not model inter-device traffic).
  * ``parity`` — correctness gates against plain ``lax.psum``: the
    unquantized path (flat AND hierarchical) must be **bitwise** equal on
    integer-valued fp32 data (any summation order is exact there); the
    quantized paths must land within a bounded relative error.
  * ``train`` — a toy data-parallel regression trained through
    ``fleet.distributed_optimizer`` with ``DistributedStrategy.
    comm_quantize`` in {"", "none", "int8", "fp8"}: rows/sec ("tok_s") per
    mode plus the final-loss delta of each quantized run vs the exact one.

Usage:
    python -m tools.collbench [--devices N] [--mb MB] [--iters K]
                              [--steps S] [--block-size B]
    python -m tools.collbench --selfcheck     # smoke: rides tier-1
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def _ensure_cpu_devices(n: int) -> None:
    """Must run BEFORE jax imports: on CPU-only hosts, force enough virtual
    XLA devices for an N-way mesh (no-op if jax is already in, e.g. when a
    harness exported its own XLA_FLAGS)."""
    if "jax" in sys.modules:
        return
    env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in env:
        os.environ["XLA_FLAGS"] = (
            env + f" --xla_force_host_platform_device_count={n}").strip()


def _mesh(devices: int):
    import numpy as np
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < devices:
        raise SystemExit(
            f"need {devices} devices, have {len(jax.devices())} "
            "(run before other jax users or set XLA_FLAGS)")
    return Mesh(np.asarray(jax.devices()[:devices]), ("dp",))


def _hier(schedule: str, devices: int):
    """Hierarchy spec for a schedule name.  On a single forced host
    jax.local_device_count()==devices so "auto" degrades to flat; the
    hierarchical rows pin an explicit 2-way intra split to exercise the
    intra-RS -> inter-AR -> intra-AG lowering."""
    if schedule == "flat":
        return None
    return 2 if devices % 2 == 0 and devices > 2 else None


def _allreduce_bench(kind, schedule, nelem, iters, devices, block_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import compress as C
    from paddle_tpu.parallel.collective import shard_map

    m = _mesh(devices)
    hier = _hier(schedule, devices)

    def ar(v):
        return C.optimized_all_reduce(v, "dp", compress=kind,
                                      block_size=block_size, hierarchy=hier,
                                      mean=False)

    f = jax.jit(shard_map(ar, mesh=m, in_specs=(P("dp"),),
                          out_specs=P("dp")))
    x = jnp.asarray(
        np.random.RandomState(0).randn(devices, nelem).astype(np.float32))
    jax.block_until_ready(f(x))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        times.append((time.perf_counter() - t0) * 1e3)
    ms = statistics.median(times)
    wire = C.wire_bytes(nelem, kind, block_size, devices)
    raw = C.wire_bytes(nelem, None, block_size, devices)
    return {
        "compress": kind or "none",
        "schedule": schedule,
        "ms": round(ms, 4),
        "gbps": round(wire / (ms / 1e3) / 1e9, 3) if ms > 0 else None,
        "wire_bytes": wire,
        "wire_ratio": round(wire / raw, 4),
    }


def _parity(nelem, devices, block_size):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import compress as C
    from paddle_tpu.parallel.collective import shard_map

    m = _mesh(devices)
    hier = _hier("hier", devices)

    def run(fn, x):
        return shard_map(fn, mesh=m, in_specs=(P("dp"),),
                         out_specs=P("dp"))(x)

    # integer-valued fp32: every summation order is exact, so bitwise
    # equality across schedules is a meaningful check
    xi = jnp.asarray(np.random.RandomState(1).randint(
        -8, 9, (devices, nelem)).astype(np.float32))
    exact_i = run(lambda v: jax.lax.psum(v, "dp"), xi)
    flat_i = run(lambda v: C.optimized_all_reduce(
        v, "dp", compress=None, hierarchy=None, mean=False), xi)
    hier_i = run(lambda v: C.optimized_all_reduce(
        v, "dp", compress=None, hierarchy=hier, mean=False), xi)
    bitwise = bool(jnp.all(exact_i == flat_i)) and \
        bool(jnp.all(exact_i == hier_i))

    xf = jnp.asarray(
        np.random.RandomState(2).randn(devices, nelem).astype(np.float32))
    exact = run(lambda v: jax.lax.psum(v, "dp"), xf)
    scale = float(jnp.max(jnp.abs(exact)))

    def rel_err(kind, hr):
        out = run(lambda v: C.optimized_all_reduce(
            v, "dp", compress=kind, block_size=block_size, hierarchy=hr,
            mean=False), xf)
        return round(float(jnp.max(jnp.abs(out - exact))) / scale, 6)

    report = {
        "unquantized_bitwise": bitwise,
        "int8_rel_err": rel_err("int8", None),
        "int8_hier_rel_err": rel_err("int8", hier),
    }
    if hasattr(jnp, "float8_e4m3fn"):
        report["fp8_rel_err"] = rel_err("fp8", None)
    return report


def _train_run(comm_quantize, steps, batch, dim, devices):
    """Toy dp regression through fleet.distributed_optimizer: returns
    (rows/sec in steady state, final loss)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.optimizer import SGD
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel.collective import shard_map
    from paddle_tpu.parallel.fleet import (DistributedOptimizer,
                                           DistributedStrategy)

    m = _mesh(devices)
    mesh_mod.set_mesh(m)
    try:
        strategy = DistributedStrategy()
        strategy.comm_quantize = comm_quantize
        strategy.comm_configs.hierarchical = _hier("hier", devices) or "off"
        opt = DistributedOptimizer(SGD(0.05), strategy)

        rng = np.random.RandomState(0)
        w_true = rng.randn(dim, 1).astype(np.float32)
        xs = jnp.asarray(rng.randn(batch, dim).astype(np.float32))
        ys = jnp.asarray((np.asarray(xs) @ w_true).astype(np.float32))
        params = {"w": jnp.zeros((dim, 1), jnp.float32)}
        state = opt.init(params)

        def step_fn(x, y, p, s):
            def loss_fn(p_):
                return jnp.mean((x @ p_["w"] - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            if not comm_quantize:
                # builder-owned sync (legacy contract when comm_quantize="")
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, "dp"), grads)
            new_p, new_s = opt.update(grads, s, p)
            return jax.lax.pmean(loss, "dp"), new_p, new_s

        f = jax.jit(shard_map(
            step_fn, mesh=m, in_specs=(P("dp"), P("dp"), P(), P()),
            out_specs=(P(), P(), P())))
        loss, params, state = f(xs, ys, params, state)  # compile + step 1
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps - 1):
            loss, params, state = f(xs, ys, params, state)
        loss = jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        tok_s = batch * max(steps - 1, 1) / dt if dt > 0 else None
        return (round(tok_s) if tok_s else None), float(loss)
    finally:
        mesh_mod.set_mesh(None)


def run_bench(args) -> dict:
    nelem = max(1024, int(args.mb * (1 << 20) / 4))
    result = {
        "bench": "collbench",
        "devices": args.devices,
        "tensor_mb": round(nelem * 4 / (1 << 20), 3),
        "block_size": args.block_size,
        "schema": 1,
    }
    import jax.numpy as jnp
    kinds = [None, "int8"] + (["fp8"] if hasattr(jnp, "float8_e4m3fn") else [])
    result["configs"] = [
        _allreduce_bench(kind, schedule, nelem, args.iters, args.devices,
                         args.block_size)
        for kind in kinds for schedule in ("flat", "hier")]
    result["parity"] = _parity(min(nelem, 1 << 15), args.devices,
                               args.block_size)
    train = {}
    losses = {}
    for mode in ("", "none", "int8") + (
            ("fp8",) if hasattr(jnp, "float8_e4m3fn") else ()):
        tok_s, loss = _train_run(mode, args.steps, args.batch, args.dim,
                                 args.devices)
        name = mode or "builder"
        train[f"tok_s_{name}"] = tok_s
        losses[name] = loss
        train[f"loss_{name}"] = round(loss, 6)
    for q in ("int8", "fp8"):
        if q in losses:
            train[f"loss_delta_{q}"] = round(
                abs(losses[q] - losses["builder"]), 6)
    result["train"] = train
    return result


def _selfcheck(result) -> int:
    """Acceptance gates (ISSUE 7): schema fields, unquantized bitwise
    parity, int8 wire ratio <= 30% of fp32, bounded quantization error,
    quantized final loss within tolerance of the exact run."""
    errors = []
    for field in ("configs", "parity", "train", "devices"):
        if field not in result:
            errors.append(f"missing field {field!r}")
    if not result.get("parity", {}).get("unquantized_bitwise"):
        errors.append("unquantized path is not bitwise-equal to lax.psum")
    int8_rows = [c for c in result.get("configs", [])
                 if c["compress"] == "int8"]
    if not int8_rows:
        errors.append("no int8 config rows")
    for c in int8_rows:
        if c["wire_ratio"] > 0.30:
            errors.append(
                f"int8 {c['schedule']} wire_ratio {c['wire_ratio']} > 0.30")
    par = result.get("parity", {})
    if par.get("int8_rel_err", 1.0) > 0.05:
        errors.append(f"int8 rel err {par.get('int8_rel_err')} > 0.05")
    if par.get("int8_hier_rel_err", 1.0) > 0.05:
        errors.append(
            f"int8 hier rel err {par.get('int8_hier_rel_err')} > 0.05")
    if "fp8_rel_err" in par and par["fp8_rel_err"] > 0.2:
        errors.append(f"fp8 rel err {par['fp8_rel_err']} > 0.2")
    train = result.get("train", {})
    if abs(train.get("loss_none", 0.0)
           - train.get("loss_builder", 1.0)) > 1e-4:
        errors.append("owned unquantized sync diverges from builder sync")
    if train.get("loss_delta_int8", 1.0) > 0.05:
        errors.append(
            f"int8 final-loss delta {train.get('loss_delta_int8')} > 0.05")
    if errors:
        print("SELFCHECK FAIL:", "; ".join(errors), file=sys.stderr)
        return 1
    print("selfcheck ok", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="collbench", description=__doc__)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--mb", type=float, default=16.0,
                   help="gradient tensor size in MB (fp32)")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--block-size", type=int, default=256)
    p.add_argument("--selfcheck", action="store_true",
                   help="small sizes + acceptance gates; exit 0/1")
    args = p.parse_args(argv)
    _ensure_cpu_devices(args.devices)
    if args.selfcheck:
        args.mb, args.iters, args.steps = 0.25, 3, 12
        args.batch, args.dim = 64, 16
    result = run_bench(args)
    print(json.dumps(result))
    if args.selfcheck:
        return _selfcheck(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
