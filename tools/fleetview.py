"""Job-level telemetry aggregation: one report over N ranks' planes.

Every rank of a ``launch --telemetry_port BASE`` job serves its own
``/metrics`` + ``/healthz`` + ``/ledger`` on ``BASE + rank``
(utils/telemetry.py) — but an operator asking "is the *job* healthy"
had to scrape and eyeball N endpoints.  fleetview is the zero-dependency
(stdlib urllib + the in-repo monitor parser) aggregator that merges them
into one job-level report:

* **cross-rank step-time skew + straggler attribution** — per-rank mean
  ``executor.step_time_ms`` reconstructed from the Prometheus histogram,
  stragglers flagged by the same leave-one-out-median rule the watchdog
  applies to heartbeat step lag, and **cross-checked** against the
  watchdog's own ``/healthz`` straggler verdict when a rank serves one
  (the two views agreeing is the acceptance bar: tests/test_fleetview.py
  injects a 5x straggler and pins identical attribution),
* **comm-bytes imbalance per mesh axis** — max/min of each rank's traced
  ``comm.allreduce_bytes`` totals,
* **goodput rollup** — min/mean of ``train.goodput_pct`` across ranks,
* **measured-vs-predicted calibration table** — ``/ledger`` records
  merged per (program x plan x mesh) key with latest + worst drift per
  cost model (utils/ledger.py bands attached),
* **job-level SLO alert plane** — ``/alerts`` scraped per rank and
  deduped by (slo, severity): one tenant's TTFT burning its budget on
  every rank is ONE job alert listing the affected ranks, not N pages.
  ``/history`` supplies per-rank ``slo.burn_rate`` series rendered as
  text-mode sparklines, and ``--gate`` makes the exit code non-zero
  while any job-level alert is firing — CI/benchdiff-style jobs fail on
  burning SLOs like on any other regression,

in ``--format text`` / ``--format json`` / ``--watch`` modes.  The JSON
report carries a flat numeric ``record`` block, so it is directly
consumable by ``tools/benchdiff`` (its ``"record"`` extractor) — fleet
skew and calibration drift gate like any other benchmark number.  This
is also the scrape client ROADMAP item 4's serving-fleet router reuses.

Usage::

    python -m tools.fleetview --base-port 9100 --nranks 4
    python -m tools.fleetview --endpoints 127.0.0.1:9100,127.0.0.1:9101
    python -m tools.fleetview --base-port 9100 --nranks 4 --watch 5
    python -m tools.fleetview --selfcheck      # tier-1 CI: in-process servers
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.utils import monitor as _monitor

__all__ = ["scrape_rank", "merge", "render_text", "selfcheck", "main"]

_DEF_TIMEOUT = 5.0
_SCRAPE_PATHS = ("/metrics", "/healthz", "/ledger", "/alerts", "/history")

# the fleet aggregator instruments itself through the same registry it
# scrapes from others (tools/metricsdump --lint inventories these)
_m_scrapes = _monitor.counter(
    "fleet.scrapes", "Rank telemetry scrapes attempted by fleetview, by "
    "endpoint path.", labelnames=("path",))
_m_scrape_errors = _monitor.counter(
    "fleet.scrape_errors", "Rank telemetry scrapes that failed (connection "
    "refused, bad body), by endpoint path.", labelnames=("path",))
_m_ranks = _monitor.gauge(
    "fleet.ranks", "Ranks merged into the last fleetview report.")


# ---------------------------------------------------------------------------
# Scraping one rank.
# ---------------------------------------------------------------------------
def _fetch(url: str, timeout: float) -> Tuple[int, str]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        # /healthz answers 503 with a full JSON body when degraded — that
        # is a *successful* scrape of an unhealthy rank, not an error
        return e.code, e.read().decode("utf-8", "replace")


def scrape_rank(endpoint: str, timeout: float = _DEF_TIMEOUT,
                since: int = 0) -> Dict[str, Any]:
    """Scrape one rank's /metrics + /healthz + /ledger + /alerts +
    /history.  Legs fail independently: a rank with a dead plane still
    appears in the merged report (with per-leg errors) instead of sinking
    the whole job view."""
    out: Dict[str, Any] = {"endpoint": endpoint}
    for path in _SCRAPE_PATHS:
        _m_scrapes.inc(path=path)
        key = path.strip("/")
        url = f"http://{endpoint}{path}"
        if path == "/ledger":
            url += f"?since={int(since)}&n=500"
        elif path == "/history":
            url += "?max_points=64"
        try:
            status, body = _fetch(url, timeout)
        except Exception as e:
            _m_scrape_errors.inc(path=path)
            out[key] = {"error": repr(e)}
            continue
        if path == "/metrics":
            try:
                out[key] = _monitor.parse_prometheus_text(body)
            except ValueError as e:
                _m_scrape_errors.inc(path=path)
                out[key] = {"error": repr(e)}
        else:
            try:
                doc = json.loads(body)
                doc["_status"] = status
                out[key] = doc
            except ValueError:
                _m_scrape_errors.inc(path=path)
                out[key] = {"error": f"bad json body (HTTP {status})"}
    return out


def _scrape_ok(leg: Any) -> bool:
    return isinstance(leg, dict) and "error" not in leg


# ---------------------------------------------------------------------------
# Prometheus-histogram reconstruction.
# ---------------------------------------------------------------------------
def _hist_stats(parsed: Dict[Tuple[str, tuple], float],
                prom_name: str) -> Optional[Dict[str, float]]:
    """mean/p50 of one exposed histogram, label cells aggregated.  The
    p50 is linearly interpolated inside the cumulative buckets — scrape-
    side reconstruction, the exact number a Prometheus `histogram_quantile`
    would compute."""
    total = count = 0.0
    buckets: Dict[float, float] = {}
    prefix_sum, prefix_count = prom_name + "_sum", prom_name + "_count"
    prefix_bucket = prom_name + "_bucket"
    for (name, labelitems), value in parsed.items():
        if name == prefix_sum:
            total += value
        elif name == prefix_count:
            count += value
        elif name == prefix_bucket:
            le = dict(labelitems).get("le", "+Inf")
            edge = float("inf") if le == "+Inf" else float(le)
            buckets[edge] = buckets.get(edge, 0.0) + value
    if count <= 0:
        return None
    target = 0.5 * count
    p50 = None
    lo_edge, lo_cum = 0.0, 0.0
    for edge in sorted(buckets):
        cum = buckets[edge]
        if cum >= target:
            if edge == float("inf") or cum <= lo_cum:
                p50 = lo_edge
            else:
                p50 = lo_edge + (edge - lo_edge) * (
                    (target - lo_cum) / (cum - lo_cum))
            break
        lo_edge, lo_cum = edge, cum
    return {"count": count, "mean": total / count,
            "p50": p50 if p50 is not None else total / count}


def _gauge_value(parsed: Dict[Tuple[str, tuple], float],
                 prom_name: str) -> Optional[float]:
    return parsed.get((prom_name, ()))


def _comm_axis_bytes(parsed: Dict[Tuple[str, tuple], float]
                     ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for (name, labelitems), value in parsed.items():
        if name == "comm_allreduce_bytes_sum":
            axis = dict(labelitems).get("axis", "?")
            out[axis] = out.get(axis, 0.0) + value
    return out


# ---------------------------------------------------------------------------
# Merging.
# ---------------------------------------------------------------------------
def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _rank_ids(scrapes: List[Dict[str, Any]]) -> List[int]:
    """Trainer ranks from /healthz; scrape order is the fallback when
    ranks are missing or collide (e.g. --selfcheck's two servers in one
    process both report the process rank)."""
    ids = []
    for idx, s in enumerate(scrapes):
        h = s.get("healthz")
        ids.append(h.get("rank") if _scrape_ok(h) else None)
    if any(r is None for r in ids) or len(set(ids)) != len(ids):
        return list(range(len(scrapes)))
    return [int(r) for r in ids]


def merge(scrapes: List[Dict[str, Any]], straggler_factor: float = 2.0,
          min_skew_ms: float = 1.0) -> Dict[str, Any]:
    """Merge per-rank scrapes into one JSON-safe job-level report.

    Straggler rule = the watchdog's (utils/watchdog.py straggler_report):
    rank r is a straggler iff its mean step time exceeds
    ``straggler_factor x`` the leave-one-out median of the others, with
    ``min_skew_ms`` as the absolute floor so idle/fast fleets don't flag
    noise.  The report cross-checks this skew-derived verdict against the
    watchdog's own heartbeat-lag verdict scraped off /healthz."""
    ranks = _rank_ids(scrapes)
    report: Dict[str, Any] = {
        "schema": "fleetview/1",
        "nranks": len(scrapes),
        "ranks": {},
    }
    step_means: Dict[int, float] = {}
    step_p50s: List[float] = []
    goodputs: List[float] = []
    axis_bytes: Dict[str, Dict[int, float]] = {}
    healthy = 0
    wd_section = None

    for rank, s in zip(ranks, scrapes):
        row: Dict[str, Any] = {"endpoint": s.get("endpoint", "")}
        h = s.get("healthz")
        if _scrape_ok(h):
            row["status"] = h.get("status", "?")
            row["healthz_rank"] = h.get("rank")
            if h.get("_status") == 200:
                healthy += 1
            wd = h.get("watchdog")
            if (wd_section is None and isinstance(wd, dict)
                    and isinstance(wd.get("stragglers"), dict)):
                wd_section = {"source_rank": rank,
                              "stragglers": wd["stragglers"].get(
                                  "stragglers", []),
                              "front_step": wd["stragglers"].get(
                                  "front_step")}
        else:
            row["status"] = "unreachable"
            row["error"] = (h or {}).get("error")
        parsed = s.get("metrics")
        if _scrape_ok(parsed):
            st = (_hist_stats(parsed, "executor_step_time_ms")
                  or _hist_stats(parsed, "train_step_time_ms"))
            if st is not None:
                step_means[rank] = st["mean"]
                step_p50s.append(st["p50"])
                row["step_time_ms"] = {
                    "mean": round(st["mean"], 4),
                    "p50": round(st["p50"], 4),
                    "count": int(st["count"])}
            gp = _gauge_value(parsed, "train_goodput_pct")
            if gp is not None:
                goodputs.append(gp)
                row["goodput_pct"] = round(gp, 2)
            for axis, nbytes in _comm_axis_bytes(parsed).items():
                axis_bytes.setdefault(axis, {})[rank] = nbytes
        led = s.get("ledger")
        if _scrape_ok(led):
            row["ledger_records"] = len(led.get("records", []))
            row["ledger_truncated"] = bool(led.get("truncated"))
        report["ranks"][str(rank)] = row

    report["healthy_ranks"] = healthy

    # -- cross-rank step-time skew + straggler attribution ----------------
    stragglers: List[int] = []
    skew = None
    if step_means:
        med = _median(list(step_means.values()))
        skew = (max(step_means.values()) / med) if med > 0 else None
        for rank, mean in sorted(step_means.items()):
            others = [v for r, v in step_means.items() if r != rank]
            if not others:
                continue
            med_o = _median(others)
            if mean > max(min_skew_ms, straggler_factor * med_o):
                stragglers.append(rank)
    report["skew"] = {
        "step_time_mean_ms": {str(r): round(v, 4)
                              for r, v in sorted(step_means.items())},
        "max_over_median": round(skew, 4) if skew is not None else None,
        "straggler_factor": straggler_factor,
        "stragglers": stragglers,
    }

    # -- cross-check against the watchdog's heartbeat attribution ---------
    if wd_section is not None:
        wd_section["agrees"] = (
            sorted(int(r) for r in wd_section["stragglers"])
            == sorted(stragglers))
    report["watchdog"] = wd_section

    # -- comm-bytes imbalance per axis ------------------------------------
    imbalance: Dict[str, Any] = {}
    for axis, per_rank in sorted(axis_bytes.items()):
        hi, lo = max(per_rank.values()), min(per_rank.values())
        imbalance[axis] = {
            "bytes": {str(r): v for r, v in sorted(per_rank.items())},
            "max_over_min": round(hi / lo, 4) if lo > 0 else None,
        }
    report["comm_imbalance"] = imbalance

    # -- goodput rollup ----------------------------------------------------
    report["goodput"] = {
        "min_pct": round(min(goodputs), 2) if goodputs else None,
        "mean_pct": round(sum(goodputs) / len(goodputs), 2)
                    if goodputs else None,
    }

    # -- measured-vs-predicted calibration table --------------------------
    report["calibration"] = _calibration_table(scrapes)

    # -- job-level SLO alert dedupe + burn-rate history -------------------
    report["alerts"] = _alerts_section(scrapes, ranks)
    report["burn_history"] = _burn_history(scrapes, ranks)

    # -- flat numeric verdict for tools/benchdiff -------------------------
    record: Dict[str, Any] = {
        "fleet": {"nranks": len(scrapes), "healthy_ranks": healthy,
                  "stragglers": len(stragglers)},
        "slo": {"alerts_firing": len(report["alerts"]["firing"]),
                "pages_firing": sum(
                    1 for a in report["alerts"]["firing"]
                    if a["severity"] == "page")},
    }
    if skew is not None:
        record["fleet"]["step_time_skew"] = round(skew, 4)
    if step_p50s:
        record["fleet"]["step_time_p50_ms"] = round(_median(step_p50s), 4)
    if goodputs:
        record["fleet"]["goodput_min_pct"] = round(min(goodputs), 2)
        record["fleet"]["goodput_mean_pct"] = round(
            sum(goodputs) / len(goodputs), 2)
    comm_rec = {f"imbalance_{axis}": doc["max_over_min"]
                for axis, doc in imbalance.items()
                if doc["max_over_min"] is not None}
    if comm_rec:
        record["comm"] = comm_rec
    worst = report["calibration"].get("worst_drift", {})
    cal_rec = {f"{model}_drift": ratio for model, ratio in worst.items()
               if ratio is not None}
    if cal_rec:
        record["calibration"] = cal_rec
    report["record"] = record

    _m_ranks.set(len(scrapes))
    return report


def _calibration_table(scrapes: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Ledger records merged per (program x plan x mesh) key: latest
    predicted/measured legs, latest + worst drift per model, and the band
    violations seen — the table autoplan's measured-vs-predicted gate
    reads."""
    bands: Dict[str, Any] = {}
    table: Dict[str, Dict[str, Any]] = {}
    worst: Dict[str, Optional[float]] = {}
    for s in scrapes:
        led = s.get("ledger")
        if not _scrape_ok(led):
            continue
        if isinstance(led.get("bands"), dict):
            bands = led["bands"]
        for rec in led.get("records", []):
            key = rec.get("key") or {}
            kid = "|".join(str(key.get(k) or "-")
                           for k in ("program", "plan", "mesh"))
            row = table.setdefault(kid, {
                "key": key, "records": 0, "band_violations": 0,
                "predicted": {}, "measured": {}, "drift": {},
                "worst_drift": {}})
            row["records"] += 1
            row["band_violations"] += len(rec.get("band_violations") or ())
            for leg in ("predicted", "measured"):
                for k, v in (rec.get(leg) or {}).items():
                    if v is not None:
                        row[leg][k] = v
            for model, ratio in (rec.get("drift") or {}).items():
                if ratio is None:
                    continue
                row["drift"][model] = round(ratio, 4)
                prev = row["worst_drift"].get(model)
                row["worst_drift"][model] = round(
                    ratio if prev is None else max(prev, ratio), 4)
                w = worst.get(model)
                worst[model] = round(
                    ratio if w is None else max(w, ratio), 4)
    return {"bands": bands, "programs": table, "worst_drift": worst}


def _alerts_section(scrapes: List[Dict[str, Any]],
                    ranks: List[int]) -> Dict[str, Any]:
    """Per-rank /alerts legs deduped into job-level alerts: one entry per
    (slo, severity) in a non-ok state, listing which ranks report it and
    the worst burn rates seen — the job view an operator (or the --gate
    exit code) acts on."""
    job: Dict[Tuple[str, str], Dict[str, Any]] = {}
    reporting = 0
    for rank, s in zip(ranks, scrapes):
        al = s.get("alerts")
        if not _scrape_ok(al):
            continue
        reporting += 1
        for a in al.get("alerts", []):
            state = a.get("state", "ok")
            if state in ("ok",):
                continue
            key = (str(a.get("slo")), str(a.get("severity")))
            row = job.setdefault(key, {
                "slo": key[0], "severity": key[1], "state": state,
                "metric": a.get("metric"), "ranks": [],
                "burn_short": 0.0, "burn_long": 0.0})
            row["ranks"].append(rank)
            row["burn_short"] = max(row["burn_short"],
                                    float(a.get("burn_short") or 0.0))
            row["burn_long"] = max(row["burn_long"],
                                   float(a.get("burn_long") or 0.0))
            # firing on ANY rank makes the job alert firing; otherwise
            # keep the most advanced state seen (pending > resolved)
            order = {"resolved": 0, "pending": 1, "firing": 2}
            if order.get(state, 0) > order.get(row["state"], 0):
                row["state"] = state
    rows = [job[k] for k in sorted(job)]
    return {
        "ranks_reporting": reporting,
        "alerts": rows,
        "firing": [r for r in rows if r["state"] == "firing"],
    }


def _burn_history(scrapes: List[Dict[str, Any]], ranks: List[int],
                  max_points: int = 32) -> Dict[str, Dict[str, List[float]]]:
    """{burn-rate series: {rank: [values]}} off the /history legs — the
    sparkline data, also JSON-exported so dashboards can re-render it."""
    out: Dict[str, Dict[str, List[float]]] = {}
    for rank, s in zip(ranks, scrapes):
        hist = s.get("history")
        if not _scrape_ok(hist):
            continue
        for name, doc in (hist.get("series") or {}).items():
            if not name.startswith("slo.burn_rate{"):
                continue
            values = [float(p[2]) for p in (doc.get("samples") or [])]
            if values:
                out.setdefault(name, {})[str(rank)] = values[-max_points:]
    return out


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[float], width: int = 24) -> str:
    """Unicode sparkline, normalized to the series max (min pinned at 0 so
    a burn rate of 0 renders as the baseline glyph)."""
    if not values:
        return ""
    if len(values) > width:
        stride = len(values) / float(width)
        values = [values[min(len(values) - 1, int(i * stride))]
                  for i in range(width)]
    hi = max(max(values), 1e-12)
    return "".join(_SPARK_GLYPHS[min(len(_SPARK_GLYPHS) - 1,
                                     int(v / hi * (len(_SPARK_GLYPHS) - 1)))]
                   for v in values)


# ---------------------------------------------------------------------------
# Rendering.
# ---------------------------------------------------------------------------
def render_text(report: Dict[str, Any]) -> str:
    lines = [f"fleetview: {report['nranks']} ranks, "
             f"{report['healthy_ranks']} healthy"]
    lines.append(f"{'rank':>5} {'status':<12} {'step p50 ms':>12} "
                 f"{'mean ms':>10} {'goodput%':>9} {'ledger':>7}")
    for rank in sorted(report["ranks"], key=lambda r: int(r)):
        row = report["ranks"][rank]
        st = row.get("step_time_ms") or {}
        p50 = f"{st['p50']:.3f}" if st else "-"
        mean = f"{st['mean']:.3f}" if st else "-"
        gp = f"{row['goodput_pct']:.1f}" if "goodput_pct" in row else "-"
        led = str(row.get("ledger_records", "-"))
        lines.append(f"{rank:>5} {row.get('status', '?'):<12} {p50:>12} "
                     f"{mean:>10} {gp:>9} {led:>7}")
    skew = report["skew"]
    lines.append(f"skew: max/median="
                 f"{skew['max_over_median'] if skew['max_over_median'] is not None else '-'}"
                 f"  stragglers={skew['stragglers'] or 'none'}")
    wd = report.get("watchdog")
    if wd is not None:
        lines.append(f"watchdog (rank {wd['source_rank']}): "
                     f"stragglers={wd['stragglers'] or 'none'}  "
                     f"agrees={'yes' if wd['agrees'] else 'NO'}")
    for axis, doc in report["comm_imbalance"].items():
        lines.append(f"comm[{axis}]: max/min={doc['max_over_min']}")
    gp = report["goodput"]
    if gp["mean_pct"] is not None:
        lines.append(f"goodput: min={gp['min_pct']}%  mean={gp['mean_pct']}%")
    alerts = report.get("alerts") or {}
    if alerts.get("alerts"):
        lines.append(f"alerts ({alerts['ranks_reporting']} ranks "
                     "reporting):")
        for a in alerts["alerts"]:
            lines.append(
                f"  {a['state'].upper():<9} {a['slo']}:{a['severity']}  "
                f"burn={a['burn_short']:.1f}/{a['burn_long']:.1f}  "
                f"ranks={a['ranks']}")
    elif alerts.get("ranks_reporting"):
        lines.append(f"alerts: none firing "
                     f"({alerts['ranks_reporting']} ranks reporting)")
    for name, per_rank in sorted((report.get("burn_history") or {}).items()):
        for rank in sorted(per_rank, key=int):
            values = per_rank[rank]
            lines.append(f"  {name} r{rank} {_sparkline(values)} "
                         f"{values[-1]:.2f}")
    cal = report["calibration"]
    if cal["programs"]:
        lines.append(f"calibration ({len(cal['programs'])} programs, "
                     f"bands={cal['bands']}):")
        lines.append(f"  {'program':<24} {'model':>9} {'drift':>8} "
                     f"{'worst':>8} {'recs':>5} {'viol':>5}")
        for kid, row in sorted(cal["programs"].items()):
            prog = (row["key"].get("program") or kid)[:24]
            for model in sorted(row["drift"]):
                lines.append(
                    f"  {prog:<24} {model:>9} {row['drift'][model]:>8} "
                    f"{row['worst_drift'][model]:>8} {row['records']:>5} "
                    f"{row['band_violations']:>5}")
                prog = ""
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Selfcheck: the tier-1 CI smoke (no subprocesses, no fixed ports).
# ---------------------------------------------------------------------------
_REPORT_KEYS = ("schema", "nranks", "healthy_ranks", "ranks", "skew",
                "watchdog", "comm_imbalance", "goodput", "calibration",
                "alerts", "burn_history", "record")


def selfcheck(verbose: bool = True) -> int:
    """Spin two in-process telemetry servers over private registries (one
    seeded 5x slower), scrape them over real HTTP, and assert the merged
    report's schema + straggler verdict.  Exercises the full wire path —
    exposition, parse round-trip, histogram reconstruction, merge."""
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.utils import telemetry as _telemetry

    saved = {"metrics": _flags.get_flag("metrics")}
    _flags.set_flags({"metrics": True})
    servers = []
    try:
        for rank, step_ms in ((0, 10.0), (1, 50.0)):
            reg = _monitor.MetricRegistry()
            hist = reg.histogram("executor.step_time_ms",
                                 "selfcheck step times")
            for _ in range(20):
                hist.observe(step_ms)
            reg.gauge("train.goodput_pct",
                      "selfcheck goodput").set(90.0 - 10.0 * rank)
            reg.histogram(
                "comm.allreduce_bytes", "selfcheck comm",
                labelnames=("axis", "dtype"),
                buckets=(1 << 10, 1 << 20),
            ).observe(1024.0 * (rank + 1), axis="dp", dtype="fp32")
            servers.append(
                _telemetry.TelemetryServer(port=0, registry=reg).start())
        scrapes = [scrape_rank(f"127.0.0.1:{s.port}") for s in servers]
        report = merge(scrapes)

        missing = [k for k in _REPORT_KEYS if k not in report]
        assert not missing, f"report missing keys: {missing}"
        assert report["nranks"] == 2
        for rank in ("0", "1"):
            assert "step_time_ms" in report["ranks"][rank], \
                f"rank {rank} metrics did not survive the wire"
        assert report["skew"]["stragglers"] == [1], report["skew"]
        # 2 ranks at 10/50 ms: median 30, skew 50/30
        assert report["record"]["fleet"]["step_time_skew"] > 1.5
        assert report["record"]["fleet"]["stragglers"] == 1
        assert report["comm_imbalance"]["dp"]["max_over_min"] == 2.0
        assert report["goodput"]["min_pct"] == 80.0
        # both /ledger legs answered (global ledger; possibly empty)
        for rank in ("0", "1"):
            assert "ledger_records" in report["ranks"][rank]
        # both /alerts legs answered (global engine; possibly not running)
        assert report["alerts"]["ranks_reporting"] == 2, report["alerts"]
        assert "alerts_firing" in report["record"]["slo"]
        json.dumps(report)  # the whole report must be JSON-clean
        if verbose:
            print(json.dumps({"selfcheck": "pass",
                              "stragglers": report["skew"]["stragglers"],
                              "skew": report["skew"]["max_over_median"]}))
        return 0
    finally:
        for s in servers:
            s.stop()
        _flags.set_flags(saved)


# ---------------------------------------------------------------------------
# CLI.
# ---------------------------------------------------------------------------
def _endpoints(args) -> List[str]:
    if args.endpoints:
        return [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if args.base_port:
        return [f"{args.host}:{args.base_port + r}"
                for r in range(args.nranks)]
    raise SystemExit("fleetview: need --endpoints or --base-port/--nranks")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.fleetview",
        description="Aggregate N ranks' telemetry planes into one "
                    "job-level report")
    parser.add_argument("--endpoints", type=str, default="",
                        help="explicit host:port list, comma-separated")
    parser.add_argument("--base-port", "--base_port", type=int, default=0,
                        dest="base_port",
                        help="scrape base_port + r for r in range(nranks) "
                        "(the launch --telemetry_port contract)")
    parser.add_argument("--nranks", type=int, default=1)
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--timeout", type=float, default=_DEF_TIMEOUT)
    parser.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                        help="re-scrape and re-render every SEC seconds")
    parser.add_argument("--out", type=str, default="",
                        help="also write the JSON report to this path")
    parser.add_argument("--selfcheck", action="store_true",
                        help="spin 2 in-process servers, scrape, assert "
                        "the merged report (CI smoke)")
    parser.add_argument("--gate", action="store_true",
                        help="exit non-zero (3) while any job-level SLO "
                        "alert is firing — CI/benchdiff-style jobs fail "
                        "on burning SLOs")
    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    endpoints = _endpoints(args)
    while True:
        scrapes = [scrape_rank(e, timeout=args.timeout) for e in endpoints]
        report = merge(scrapes)
        if args.format == "json":
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print(render_text(report), end="")
        if args.out:
            with open(args.out, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        if args.gate and report["alerts"]["firing"]:
            names = [f"{a['slo']}:{a['severity']}"
                     for a in report["alerts"]["firing"]]
            print(f"fleetview: gate FAILED — firing: {', '.join(names)}",
                  file=sys.stderr)
            return 3
        if not args.watch:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
        print()


if __name__ == "__main__":
    sys.exit(main())
