"""Serving-frontend load generator: closed-loop and open-loop (qps ramp)
benchmarks of ``paddle_tpu.serving`` plus the continuous-batching decode
path, printing exactly ONE JSON line (BENCH_SERVE.json schema).

What it measures:

* ``baseline`` — closed loop, ONE client: every request is dispatched
  alone (batch of 1).  This is the reference predictor-pool model (one
  AnalysisPredictor::Run per request) and the denominator of ``speedup``.
* ``batched`` — closed loop, ``--clients`` concurrent submitters
  coalescing through the shape-bucket frontend.  ``speedup`` =
  batched qps / baseline qps — the throughput the server-side batching
  buys at equal work per request (acceptance floor: >= 3x on a host where
  per-dispatch overhead dominates small-model step time).
* ``open_loop`` — requests injected at fixed target rates
  (``--qps-ramp``, e.g. "50,100,200"), one record per level: achieved
  qps, latency percentiles, and how many requests the SLO/quota admission
  shed.  Unlike the closed loop, this shows saturation: achieved qps
  flattens and p99 blows up past the knee.
* ``continuous`` — iteration-level decode of ``--seqs`` prompts on a
  ``--slots``-slot pool vs the same prompts decoded sequentially
  (single-slot pool = request-level batching floor), with per-sequence
  token parity (``parity`` MUST be true: slot placement never changes a
  sequence's tokens).
* ``occupancy_hist`` — the ``serve.batch_size`` histogram observed during
  the batched phase: how full the dispatched buckets actually were.
* ``paged`` (``--paged``) — the paged-KV serving blocks
  (``serving/paged.py``): ``capacity`` measures max concurrent short
  sequences admitted at a FIXED KV-pool HBM budget vs the dense
  slot-reservation equivalent (every slot provisioned for ``max_len``);
  ``decode`` races paged decode against a ``ContinuousBatcher`` given the
  SAME HBM (the dense pool affords only ``pool_bytes / max_len-row``
  slots) with per-sequence token parity vs a straight-line dense
  reference decode; ``ttft_mix`` joins a long prompt and measures how
  much short-request first-token latency moves when chunked prefill
  interleaves it (steps and wall ms, alone vs mixed); ``prefix_cache``
  replays a shared-system-prompt workload and reports the block hit rate
  plus prefill chunks cold vs warm.

Latency percentiles come from the SAME ``Histogram.percentile`` estimator
the SLO admission uses (one quantile implementation everywhere).

Usage:
    python -m tools.servebench [--clients N] [--duration S] [--hidden H]
                               [--buckets 1,2,4,8,16,32] [--max-wait-ms W]
                               [--qps-ramp 50,100,200] [--slo-p99-ms MS]
                               [--seqs N] [--slots N] [--new-tokens N]
                               [--paged] [--out FILE]
    python -m tools.servebench --selfcheck     # smoke: rides tier-1
"""
from __future__ import annotations

import argparse
import json
import math
import sys
import threading
import time


def _percentiles(lat_ms):
    import numpy as np

    if not lat_ms:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    a = np.asarray(lat_ms, dtype=np.float64)
    return {"p50_ms": round(float(np.percentile(a, 50)), 4),
            "p95_ms": round(float(np.percentile(a, 95)), 4),
            "p99_ms": round(float(np.percentile(a, 99)), 4)}


def _build_tenant(hidden: int):
    """A small row-independent inference graph (dims chosen well clear of
    the degenerate gemm shapes where XLA:CPU picks batch-dependent kernel
    strategies — see tests/test_serving.py)."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    main.random_seed = 11
    startup.random_seed = 11
    scope = static.Scope()
    with static.program_guard(main, startup), static.scope_guard(scope):
        x = L.data("x", [hidden])
        y = L.fc(L.fc(x, 2 * hidden, act="tanh"), hidden)
        exe = static.Executor()
        exe.run(startup, scope=scope)
    return main, y, scope


def _mk_server(serving, edges, max_wait_ms, slo_p99_ms=None):
    slo = serving.SLOPolicy(p99_ms=slo_p99_ms)
    return serving.Server(bucket_edges=edges, max_wait_ms=max_wait_ms,
                          slo=slo)


def _closed_loop(srv, rows_feed, clients: int, duration: float):
    """``clients`` threads each submit-and-wait in a loop for ``duration``
    seconds; returns (achieved_qps, latencies_ms)."""
    lat_ms, lock = [], threading.Lock()
    stop = time.perf_counter() + duration

    def client():
        mine = []
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            srv.submit("bench", rows_feed).result()
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            lat_ms.extend(mine)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client) for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return (len(lat_ms) / wall if wall > 0 else 0.0), lat_ms


def _open_loop(srv, rows_feed, qps: float, duration: float):
    """Inject at a fixed target rate (no waiting for results); returns
    (achieved_qps, latencies_ms, shed_count)."""
    from paddle_tpu.serving import AdmissionError

    lat_ms, lock = [], threading.Lock()
    shed = [0]
    pending = []
    period = 1.0 / qps
    t_start = time.perf_counter()
    n = 0
    while True:
        target = t_start + n * period
        now = time.perf_counter()
        if now >= t_start + duration:
            break
        if now < target:
            time.sleep(min(target - now, 0.01))
            continue
        t0 = time.perf_counter()
        try:
            fut = srv.submit("bench", rows_feed)
        except AdmissionError:
            shed[0] += 1
            n += 1
            continue

        def done(f, t0=t0):
            with lock:
                if f.exception() is None:
                    lat_ms.append((time.perf_counter() - t0) * 1e3)
        fut.add_done_callback(done)
        pending.append(fut)
        n += 1
    for f in pending:
        try:
            f.result(timeout=60)
        except Exception:
            pass
    wall = time.perf_counter() - t_start
    return (len(lat_ms) / wall if wall > 0 else 0.0), lat_ms, shed[0]


def _continuous(seqs: int, slots: int, new_tokens: int):
    """Multi-slot continuous decode vs sequential single-slot decode of the
    same prompts: tokens/s both ways + per-sequence token parity."""
    from paddle_tpu.serving import ContinuousBatcher, make_toy_lm

    max_len = 8 + new_tokens
    step_fn, init_fn = make_toy_lm(vocab=64, hidden=16, max_len=max_len,
                                   seed=3)
    prompts = [[(7 * i + j) % 64 for j in range(2 + i % 5)]
               for i in range(seqs)]

    cb = ContinuousBatcher(step_fn, init_fn, num_slots=slots,
                           max_len=max_len)
    cb.decode(prompts[:1], max_new_tokens=new_tokens)  # compile, off-clock
    t0 = time.perf_counter()
    multi = cb.decode(prompts, max_new_tokens=new_tokens)
    t_multi = time.perf_counter() - t0

    seq = ContinuousBatcher(step_fn, init_fn, num_slots=1, max_len=max_len)
    seq.decode(prompts[:1], max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    sequential = [seq.decode([p], max_new_tokens=new_tokens)[0]
                  for p in prompts]
    t_seq = time.perf_counter() - t0

    toks = sum(len(t) for t in multi)
    return {
        "sequences": seqs, "slots": slots, "new_tokens": new_tokens,
        "tok_s_continuous": round(toks / t_multi, 1) if t_multi else None,
        "tok_s_sequential": round(toks / t_seq, 1) if t_seq else None,
        "decode_speedup": round(t_seq / t_multi, 2) if t_multi else None,
        "parity": multi == sequential,
    }


def _paged(seqs: int, new_tokens: int):
    """The paged-KV serving blocks: fixed-HBM concurrency, decode tok/s at
    equal HBM vs the continuous path, chunked-prefill TTFT isolation, and
    prefix-cache hit rate."""
    import numpy as np

    from paddle_tpu.serving import ContinuousBatcher, make_toy_lm
    from paddle_tpu.serving import paged as P

    hidden, bs, nb, maxb = 32, 8, 64, 32
    max_len = maxb * bs                      # the provisioned capability
    model = P.make_paged_toy_lm(vocab=64, hidden=hidden, max_positions=512,
                                seed=3)
    rec = {"block_size": bs, "num_blocks": nb, "max_blocks_per_seq": maxb,
           "hidden": hidden}

    # -- capacity: short requests admitted at fixed pool HBM ------------------
    # 9 prompt + 7 new = 16 tokens = exactly 2 blocks per sequence, so the
    # admission count is pure allocator physics (no decode-time growth).
    # The dense equivalent reserves max_blocks_per_seq per slot (every
    # sequence provisioned for max_len — the ContinuousBatcher model).
    cache = P.PagedKVCache(model, nb, bs)
    dec = P.PagedDecoder(model, cache, max_seqs=nb,
                         max_blocks_per_seq=maxb)
    rng = np.random.default_rng(5)
    handles = []
    while True:
        h = dec.try_join([int(t) for t in rng.integers(0, 64, 9)], 7)
        if h is None:
            break
        handles.append(h)
    paged_cap = len(handles)
    for h in handles:
        dec.evict(h)
    dense_slots_cap = max(1, nb // maxb)
    rec["capacity"] = {
        "pool_bytes": cache.bytes, "paged_concurrent": paged_cap,
        "dense_slots": dense_slots_cap,
        "concurrent_speedup": round(paged_cap / dense_slots_cap, 2)}

    # -- decode tok/s at equal HBM vs the continuous path ---------------------
    cache = P.PagedKVCache(model, nb, bs)
    dec = P.PagedDecoder(model, cache, max_seqs=16,
                         max_blocks_per_seq=maxb)
    prompts = [[int(t) for t in rng.integers(0, 64, 4)] for _ in range(seqs)]
    dec.decode(prompts[:1], max_new_tokens=new_tokens)  # compile, off-clock
    t_paged = math.inf
    for _ in range(3):                       # best-of-3 rides out host noise
        t0 = time.perf_counter()
        paged_out = dec.decode(prompts, max_new_tokens=new_tokens)
        t_paged = min(t_paged, time.perf_counter() - t0)
    parity = all(
        paged_out[i] == P.dense_reference_decode(model, prompts[i],
                                                 new_tokens)
        for i in range(min(4, seqs)))

    # the dense pool gets the SAME bytes: rows provisioned at max_len
    dense_row = max_len * hidden * 4
    cont_slots = max(1, int(cache.bytes // dense_row))
    step_fn, init_fn = make_toy_lm(vocab=64, hidden=hidden, max_len=max_len,
                                   seed=3)
    cb = ContinuousBatcher(step_fn, init_fn, num_slots=cont_slots,
                           max_len=max_len)
    cb.decode(prompts[:1], max_new_tokens=new_tokens)
    t_cont = math.inf
    for _ in range(3):
        t0 = time.perf_counter()
        cb.decode(prompts, max_new_tokens=new_tokens)
        t_cont = min(t_cont, time.perf_counter() - t0)
    toks = sum(len(t) for t in paged_out)
    rec["decode"] = {
        "sequences": seqs, "max_seqs": 16,
        "dense_slots_equal_hbm": cont_slots,
        "tok_s_paged": round(toks / t_paged, 1) if t_paged else None,
        "tok_s_continuous": round(toks / t_cont, 1) if t_cont else None,
        "decode_speedup": round(t_cont / t_paged, 2) if t_paged else None,
        "parity": parity}

    # -- chunked prefill: short-request TTFT, alone vs long-prompt mix --------
    chunk = 4
    long_tokens, short_tokens, n_short = 64, 6, 4

    def _ttft(with_long: bool):
        c = P.PagedKVCache(model, nb, bs)
        d = P.PagedDecoder(model, c, max_seqs=8, max_blocks_per_seq=16,
                           prefill_chunk=chunk)
        # compile off-clock across the gather-width ladder both runs will
        # touch (the step width tracks the longest live table, so the long
        # prompt and the shorts hit different compiled shapes)
        d.decode([[1, 2, 3]], 2)
        d.decode([[int(t) for t in rng.integers(0, 64, short_tokens)]],
                 short_tokens)
        d.decode([[int(t) for t in rng.integers(0, 64, long_tokens)]], 4)
        if with_long:
            d.join([int(t) for t in rng.integers(0, 64, long_tokens)], 4)
        shorts = [d.join([int(t) for t in rng.integers(0, 64,
                                                       short_tokens)], 4)
                  for _ in range(n_short)]
        ttft_ms, ttft_steps = {}, {}
        steps = 0
        while d.active_count:
            d.step()
            steps += 1
            now = time.perf_counter()
            for i, h in enumerate(shorts):
                if h.tokens and i not in ttft_ms:
                    ttft_ms[i] = (now - h._t_submit) * 1e3
                    ttft_steps[i] = steps
        return list(ttft_ms.values()), max(ttft_steps.values())

    alone_ms, alone_steps = _ttft(with_long=False)
    mixed_ms, mixed_steps = _ttft(with_long=True)
    rec["ttft_mix"] = {
        "long_tokens": long_tokens, "short_tokens": short_tokens,
        "prefill_chunk": chunk,
        "short_ttft_alone_p99_ms": _percentiles(alone_ms)["p99_ms"],
        "short_ttft_mixed_p99_ms": _percentiles(mixed_ms)["p99_ms"],
        "short_ttft_alone_steps": alone_steps,
        "short_ttft_mixed_steps": mixed_steps}

    # -- prefix cache: shared system prompt, unique suffixes ------------------
    cache = P.PagedKVCache(model, nb, bs)
    dec = P.PagedDecoder(model, cache, max_seqs=4, max_blocks_per_seq=16)
    sys_prompt = [int(t) for t in rng.integers(0, 64, 32)]
    n_req = 8
    lookups_per_req = (len(sys_prompt) + 3 - 1) // bs   # full blocks probed
    h0 = P.KV_PREFIX_HITS.value()
    c0 = P.KV_PREFILL_CHUNKS.value()
    dec.decode([sys_prompt + [int(t) for t in rng.integers(0, 64, 3)]], 4)
    cold_chunks = P.KV_PREFILL_CHUNKS.value() - c0
    c1 = P.KV_PREFILL_CHUNKS.value()
    for _ in range(n_req - 1):
        dec.decode([sys_prompt + [int(t) for t in rng.integers(0, 64, 3)]],
                   4)
    warm_chunks = (P.KV_PREFILL_CHUNKS.value() - c1) / (n_req - 1)
    hits = P.KV_PREFIX_HITS.value() - h0
    rec["prefix_cache"] = {
        "requests": n_req, "system_prompt_tokens": len(sys_prompt),
        "prefix_hits": int(hits),
        "hit_rate": round(hits / (n_req * lookups_per_req), 3),
        "prefill_chunks_cold": int(cold_chunks),
        "prefill_chunks_warm_mean": round(warm_chunks, 2)}
    return rec


def _occupancy_hist():
    """The serve.batch_size histogram (cumulative bucket counts) from the
    metrics registry — how full dispatched batches were."""
    from paddle_tpu.utils import monitor

    doc = monitor.default_registry().to_json()
    m = doc.get("metrics", {}).get("serve.batch_size")
    for s in (m or {}).get("samples", []):
        return {"buckets": s.get("buckets", {}),
                "count": s.get("count"),
                "mean": (round(s["sum"] / s["count"], 2)
                         if s.get("count") else None)}
    return None


def run_bench(args) -> dict:
    import numpy as np

    from paddle_tpu import serving
    from paddle_tpu.core import flags

    flags.set_flags({"metrics": True})  # occupancy hist + SLO data
    edges = tuple(int(e) for e in args.buckets.split(","))
    main, y, scope = _build_tenant(args.hidden)
    rng = np.random.default_rng(0)
    rows_feed = {"x": rng.normal(size=(1, args.hidden)).astype(np.float32)}

    record = {"bench": "servebench", "schema": 1, "hidden": args.hidden,
              "buckets": list(edges), "max_wait_ms": args.max_wait_ms,
              "clients": args.clients}

    # baseline: one closed-loop client == single-request-at-a-time.
    # max_wait_ms=0 so the dispatcher never holds its lone request open
    # waiting for rows that cannot come — the honest serialized floor
    with _mk_server(serving, edges, 0.0) as srv:
        srv.add_tenant("bench", main, ["x"], [y], scope)
        srv.submit("bench", rows_feed).result()  # compile b1, off-clock
        qps0, lat0 = _closed_loop(srv, rows_feed, 1, args.duration)
    record["baseline"] = {"qps": round(qps0, 1), **_percentiles(lat0)}

    # batched: N concurrent closed-loop clients through the bucket ladder
    with _mk_server(serving, edges, args.max_wait_ms) as srv:
        srv.add_tenant("bench", main, ["x"], [y], scope)
        srv.submit("bench", rows_feed).result()
        qps1, lat1 = _closed_loop(srv, rows_feed, args.clients,
                                  args.duration)
    record["batched"] = {"qps": round(qps1, 1), **_percentiles(lat1)}
    record["speedup"] = round(qps1 / qps0, 2) if qps0 else None
    record["occupancy_hist"] = _occupancy_hist()

    # open loop: ramp the injection rate, watch saturation + shedding
    if args.qps_ramp:
        levels = []
        for qps in (float(q) for q in args.qps_ramp.split(",")):
            with _mk_server(serving, edges, args.max_wait_ms,
                            slo_p99_ms=args.slo_p99_ms) as srv:
                srv.add_tenant("bench", main, ["x"], [y], scope)
                srv.submit("bench", rows_feed).result()
                aq, lats, shed = _open_loop(srv, rows_feed, qps,
                                            args.duration)
            levels.append({"target_qps": qps, "achieved_qps": round(aq, 1),
                           "shed": shed, **_percentiles(lats)})
        record["open_loop"] = levels

    record["continuous"] = _continuous(args.seqs, args.slots,
                                       args.new_tokens)
    if args.paged:
        record["paged"] = _paged(args.seqs, args.new_tokens)
    return record


def _selfcheck() -> int:
    ns = _parser().parse_args(
        ["--duration", "0.8", "--clients", "8", "--buckets", "1,2,4,8",
         "--qps-ramp", "40", "--seqs", "6", "--slots", "4",
         "--new-tokens", "5", "--hidden", "16", "--paged"])
    rec = run_bench(ns)
    assert rec["baseline"]["qps"] > 0 and rec["batched"]["qps"] > 0
    assert rec["baseline"]["p99_ms"] is not None
    assert rec["continuous"]["parity"] is True, "decode parity broken"
    assert rec["occupancy_hist"] is not None
    assert rec["open_loop"][0]["achieved_qps"] > 0
    pg = rec["paged"]
    assert pg["decode"]["parity"] is True, "paged decode parity broken"
    assert pg["capacity"]["concurrent_speedup"] > 1
    assert pg["prefix_cache"]["prefix_hits"] > 0
    assert pg["prefix_cache"]["prefill_chunks_warm_mean"] < \
        pg["prefix_cache"]["prefill_chunks_cold"]
    print(json.dumps(rec))
    print("servebench selfcheck: OK")
    return 0


def _parser():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per load phase")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--buckets", default="1,2,4,8,16,32")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--qps-ramp", default="",
                    help="comma-separated open-loop target qps levels")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="enable SLO load-shedding in the open-loop phases")
    ap.add_argument("--seqs", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--paged", action="store_true",
                    help="run the paged-KV serving blocks (capacity, "
                         "decode vs continuous, TTFT mix, prefix cache)")
    ap.add_argument("--out", default="",
                    help="also write the BENCH_SERVE.json document here")
    ap.add_argument("--selfcheck", action="store_true")
    return ap


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    rec = run_bench(args)
    line = json.dumps(rec)
    print(line)
    if args.out:
        doc = {
            "_note": ("servebench run on XLA:CPU — absolute qps measures "
                      "host dispatch, not TPU compute; 'speedup' (server-"
                      "side batching vs single-request-at-a-time) and "
                      "'continuous.parity' are the portable numbers."),
            "environment": "cpu",
            "record": rec,
        }
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
