"""proglint — repo-level static lint for op lowering modules.

The runtime program verifier (paddle_tpu/static/analysis.py) checks
*Programs*; this tool checks the *lowering rules themselves* at the source
level, AST-based, so violations gate tier-1 through
tests/test_analysis.py::test_proglint_clean_on_repo instead of surfacing as
trace-time heisenbugs.  Checks:

- ``PL001`` host-side nondeterminism inside a lowering module: calls
  through ``numpy.random`` / stdlib ``random`` / ``time`` / ``datetime``.
  Lowering rules run under jax.jit tracing — host randomness is baked into
  the compiled executable once and silently replayed every step (the
  sanctioned path is ``core.random.next_key()``, which folds per-op PRNG
  scopes; see executor._run_op_traced).
- ``PL002`` return-contract violations in a registered lowering: the
  registry contract is ``{slot: [arrays]}`` (static/registry.py) — a dict
  literal return with a non-string key or a non-list/tuple value, or a
  bare/None return, is flagged.  Returns of names/calls are not provable
  statically and are skipped.
- ``PL003`` a ``register_op`` name that collides with
  ``op_coverage.DESCOPED``: the op is simultaneously claimed descoped and
  registered — one of the two claims is stale.
- ``PL004`` the same op name registered twice across the scanned files
  (the runtime registry raises at import; the lint catches it without
  importing).
- ``PL005`` host-sync APIs inside traced code: ``np.asarray``/``np.array``
  on traced values, ``jax.device_get``, or ``.block_until_ready()`` in the
  body of a lowering (any function with the universal ``(ins, attrs, op)``
  signature, however it is registered).  Under jit these either concretize
  a tracer (ConcretizationTypeError at trace time) or stall the dispatch
  pipeline per step.  Calls whose argument subtree only touches ``attrs``
  are exempt (attrs are compile-time constants), nested helper functions
  are exempt (host callbacks run outside the trace), and a deliberate
  static-shape-contract site is waived with a ``# proglint: host-sync-ok``
  comment on the same line.

- ``PL006`` raw Program graph mutation outside the sanctioned Block/
  Program API: calling list mutators (append/insert/pop/remove/clear/
  extend/sort/reverse) on a ``.ops``/``.blocks`` attribute, assigning or
  ``del``-ing into them, rebinding them, or writing ``._version``
  directly.  Every sanctioned mutation (framework.py append_op/insert_op/
  remove_op/replace_op/set_ops/remove_var/bump_version) bumps
  ``Program._version``, which keys the analysis memo, the shardcheck
  memo, and the Executor's hot cache — a raw mutation silently serves
  stale verdicts and stale executables.  framework.py itself (the API) is
  exempt; a deliberate site is waived with ``# proglint: raw-mutation-ok``
  on the same line.  This check scans the whole static-graph surface
  (``paddle_tpu/static/``, ``paddle_tpu/slim/``, ``tools/``), not just
  lowering modules.

- ``PL007`` dense O(vocab)/O(param) intermediates in a lowering module: a
  ``jnp.zeros``/``ones``/``full`` (or ``*_like``) buffer whose size comes
  from a *runtime array* (a ``.shape`` access or a ``_like`` callee) used
  directly as a scatter target (``.at[...]``).  This is the embedding-
  gradient anti-pattern: ``jnp.zeros(table.shape).at[ids].add(g)``
  materializes the whole table per step, which memcheck's MC003 sees as
  an O(vocab) transient.  Constant- or attrs-sized buffers are exempt
  (compile-time bounded); a deliberately-bounded site (e.g. the padded
  static-shape ``unique`` contract, or a center-loss table update that IS
  the op's semantics) is waived with ``# proglint: dense-intermediate-ok``
  on the allocation's line or the line above it.

CLI:  ``python -m tools.proglint [files...]`` — defaults to every
``paddle_tpu/static/ops*.py`` in the repo for PL001–PL005 plus the
static-graph surface for PL006; exits 0 when clean, 1 when any violation
is found.  Dependency-free: op_coverage.py is exec'd standalone (it is a
pure data module) rather than imported through the package, so the lint
runs without jax.
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Sequence

REPO_ROOT = Path(__file__).resolve().parent.parent
OPS_GLOB = "paddle_tpu/static/ops*.py"

# modules whose use inside a lowering module means host-side nondeterminism
_FORBIDDEN_MODULES = {
    "random": "stdlib random",
    "time": "time",
    "datetime": "datetime",
}
# attributes of numpy that are forbidden (np.random.*)
_FORBIDDEN_NUMPY_ATTRS = {"random"}


class Violation(NamedTuple):
    path: str
    line: int
    code: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _load_descoped() -> Dict[str, str]:
    """Exec op_coverage.py standalone — it is a pure-data module with no
    package-relative imports, so this avoids importing jax."""
    path = REPO_ROOT / "paddle_tpu" / "static" / "op_coverage.py"
    ns: Dict = {}
    exec(compile(path.read_text(), str(path), "exec"), ns)
    return ns["DESCOPED"]


def _module_aliases(tree: ast.Module) -> Dict[str, str]:
    """local-name -> canonical module for numpy + forbidden stdlib modules."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root == "numpy" or root in _FORBIDDEN_MODULES:
                    aliases[a.asname or root] = root
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root == "numpy":
                for a in node.names:
                    if a.name in _FORBIDDEN_NUMPY_ATTRS:
                        aliases[a.asname or a.name] = "numpy.random"
            elif root in _FORBIDDEN_MODULES and node.level == 0:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{root}.{a.name}"
    return aliases


def _register_op_name(dec: ast.expr) -> Optional[str]:
    """The constant op name of a `@register_op("x")` decorator / call."""
    if (isinstance(dec, ast.Call) and dec.args
            and isinstance(dec.func, ast.Name)
            and dec.func.id == "register_op"
            and isinstance(dec.args[0], ast.Constant)
            and isinstance(dec.args[0].value, str)):
        return dec.args[0].value
    return None


def _check_forbidden_idioms(path: str, tree: ast.Module,
                            out: List[Violation]) -> None:
    aliases = _module_aliases(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name):
            base = aliases.get(node.value.id)
            if base == "numpy" and node.attr in _FORBIDDEN_NUMPY_ATTRS:
                out.append(Violation(
                    path, node.lineno, "PL001",
                    f"numpy.random used in a lowering module (as "
                    f"{node.value.id}.{node.attr}) — host randomness is "
                    "baked into the trace; use core.random.next_key()"))
            elif base in _FORBIDDEN_MODULES:
                out.append(Violation(
                    path, node.lineno, "PL001",
                    f"{base}.{node.attr} used in a lowering module — "
                    "host-side nondeterminism is baked into the trace"))
        elif isinstance(node, ast.Name) and aliases.get(
                node.id, "").startswith(("numpy.random", "random.",
                                         "time.", "datetime.")):
            out.append(Violation(
                path, node.lineno, "PL001",
                f"{aliases[node.id]} (bound as {node.id!r}) used in a "
                "lowering module — host-side nondeterminism is baked "
                "into the trace"))


def _check_return_contract(path: str, fn: ast.FunctionDef, op_name: str,
                           out: List[Violation]) -> None:
    """Flag provably-wrong returns in a registered lowering: the registry
    contract is {slot: [arrays]}."""
    for node in _own_statements(fn):
        if not isinstance(node, ast.Return):
            continue
        value = node.value
        if value is None or (isinstance(value, ast.Constant)
                             and value.value is None):
            out.append(Violation(
                path, node.lineno, "PL002",
                f"lowering {op_name!r} returns None — the registry "
                "contract is {slot: [arrays]}"))
        elif isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if k is None:
                    continue                      # **spread: not provable
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    out.append(Violation(
                        path, k.lineno, "PL002",
                        f"lowering {op_name!r} returns a dict with a "
                        "non-string slot key"))
                if isinstance(v, ast.Constant) or isinstance(v, ast.Dict):
                    out.append(Violation(
                        path, v.lineno, "PL002",
                        f"lowering {op_name!r} returns a slot value that "
                        "is not a list of arrays — the contract is "
                        "{'Out': [value]}"))
        elif isinstance(value, (ast.List, ast.Tuple, ast.Constant)):
            out.append(Violation(
                path, node.lineno, "PL002",
                f"lowering {op_name!r} returns "
                f"{type(value).__name__} — the registry contract is a "
                "dict {slot: [arrays]}"))


_HOST_SYNC_WAIVER = "proglint: host-sync-ok"
_LOWERING_ARGS = ("ins", "attrs", "op")


def _is_lowering_fn(node) -> bool:
    """A lowering rule is any function with the universal registry
    signature (ins, attrs, op) — decorator-registered, call-registered, a
    factory's nested `rule`, or a lambda."""
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
        return False
    args = node.args
    names = tuple(a.arg for a in args.args)
    return (names == _LOWERING_ARGS and not args.posonlyargs
            and not args.kwonlyargs)


def _touches_only_attrs(call: ast.Call) -> bool:
    """True when every Name the call's arguments read is `attrs` (or a
    builtin-looking constant path): attrs are compile-time constants, so
    np.asarray over them never syncs a tracer."""
    loads = [n for a in call.args + [kw.value for kw in call.keywords]
             for n in ast.walk(a) if isinstance(n, ast.Name)]
    return bool(loads) and all(
        n.id in ("attrs", "np", "numpy", "jnp", "list", "tuple", "int",
                 "float", "len", "sorted") for n in loads)


def _check_host_sync(path: str, fn, aliases: Dict[str, str], lines,
                     out: List[Violation]) -> None:
    body = fn.body if isinstance(fn, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue                    # host callbacks run off-trace
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        finding = None
        if isinstance(func, ast.Attribute):
            if (isinstance(func.value, ast.Name)
                    and aliases.get(func.value.id) == "numpy"
                    and func.attr in ("asarray", "array")):
                finding = (f"np.{func.attr} on a traced value forces a "
                           "host sync / concretization inside the trace")
            elif (isinstance(func.value, ast.Name)
                  and func.value.id == "jax"
                  and func.attr == "device_get"):
                finding = ("jax.device_get inside a lowering blocks on "
                           "device work every trace")
            elif func.attr == "block_until_ready":
                finding = (".block_until_ready() inside a lowering stalls "
                           "the dispatch pipeline")
        if finding is None:
            continue
        if _touches_only_attrs(node):
            continue                    # attrs are compile-time constants
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _HOST_SYNC_WAIVER in line:
            continue
        out.append(Violation(
            path, node.lineno, "PL005",
            finding + " — hoist to attrs, use jnp, or move it into a host "
            f"callback (waive a deliberate static-shape contract with "
            f"`# {_HOST_SYNC_WAIVER}`)"))


_DENSE_WAIVER = "proglint: dense-intermediate-ok"
_DENSE_ALLOCS = frozenset((
    "zeros", "zeros_like", "ones", "ones_like", "full", "full_like",
    "empty", "empty_like"))


def _sized_from_runtime_array(call: ast.Call) -> bool:
    """True when the allocation's extent is tied to a runtime array: a
    ``*_like`` callee, or a ``.shape`` access anywhere in the arguments.
    Constant / attrs-derived sizes are compile-time bounded and exempt."""
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr.endswith("_like"):
        return True
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        for n in ast.walk(a):
            if isinstance(n, ast.Attribute) and n.attr == "shape":
                return True
    return False


def _check_dense_intermediate(path: str, tree: ast.Module, lines,
                              out: List[Violation]) -> None:
    """PL007: an input-sized dense buffer immediately scattered into —
    the anti-pattern memcheck prices as an O(vocab)/O(param) transient."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Attribute) and node.attr == "at"
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in _DENSE_ALLOCS
                and isinstance(f.value, ast.Name)
                and f.value.id in ("jnp", "np", "numpy")):
            continue
        if not _sized_from_runtime_array(call):
            continue
        waiver_lines = lines[max(0, call.lineno - 2):call.lineno]
        if any(_DENSE_WAIVER in ln for ln in waiver_lines):
            continue
        out.append(Violation(
            path, call.lineno, "PL007",
            f"`{f.value.id}.{f.attr}` sized from a runtime array is used "
            "as a scatter target — this materializes a dense "
            "O(param)/O(vocab) intermediate every step (dedup the ids or "
            "use segment ops; waive a deliberately-bounded site with "
            f"`# {_DENSE_WAIVER}`)"))


_RAW_MUTATION_WAIVER = "proglint: raw-mutation-ok"
_MUTATING_LIST_METHODS = frozenset((
    "append", "insert", "pop", "remove", "clear", "extend", "sort",
    "reverse"))
_GRAPH_ATTRS = ("ops", "blocks")


def _is_graph_list(expr) -> bool:
    return isinstance(expr, ast.Attribute) and expr.attr in _GRAPH_ATTRS


def _check_raw_mutation(path: str, tree: ast.Module, lines,
                        out: List[Violation]) -> None:
    """PL006: Program graph state must change through the sanctioned
    mutation API so ``Program._version`` tracks every change."""

    def flag(node, what: str) -> None:
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _RAW_MUTATION_WAIVER in line:
            return
        out.append(Violation(
            path, node.lineno, "PL006",
            f"{what} bypasses the Block/Program mutation API — "
            "program._version will not track the change, so the analysis "
            "memo, shardcheck memo, and Executor hot cache go stale "
            "(use append_op/insert_op/remove_op/replace_op/set_ops/"
            "remove_var/bump_version, or waive a deliberate site with "
            f"`# {_RAW_MUTATION_WAIVER}`)"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _MUTATING_LIST_METHODS
                    and _is_graph_list(f.value)):
                flag(node, f"`.{f.value.attr}.{f.attr}()`")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and _is_graph_list(t.value):
                    flag(node, f"item assignment into `.{t.value.attr}`")
                elif isinstance(t, ast.Attribute) and t.attr in _GRAPH_ATTRS:
                    flag(node, f"rebinding `.{t.attr}`")
                elif isinstance(t, ast.Attribute) and t.attr == "_version":
                    flag(node, "a direct `._version` write")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and _is_graph_list(t.value):
                    flag(node, f"`del` on `.{t.value.attr}`")


def lint_raw_mutation(path) -> List[Violation]:
    """Run only the PL006 check over one file (any static-graph module,
    not just lowerings).  framework.py is the API itself — exempt."""
    path = Path(path)
    if path.name == "framework.py":
        return []
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    out: List[Violation] = []
    _check_raw_mutation(str(path), tree, source.splitlines(), out)
    return out


def mutation_targets() -> List[Path]:
    """The static-graph surface PL006 scans by default: everywhere
    Programs are built or rewritten."""
    out = []
    for pattern in ("paddle_tpu/static/**/*.py", "paddle_tpu/slim/**/*.py",
                    "tools/*.py"):
        out.extend(REPO_ROOT.glob(pattern))
    return sorted(p for p in out if p.name != "framework.py")


def _own_statements(fn: ast.FunctionDef):
    """Walk fn's statements WITHOUT descending into nested function defs
    (a nested helper's returns are not the lowering's returns)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def lint_file(path, descoped: Optional[Dict[str, str]] = None,
              seen_names: Optional[Dict[str, str]] = None
              ) -> List[Violation]:
    """Lint one lowering module; returns its violations."""
    path = Path(path)
    rel = str(path)
    descoped = _load_descoped() if descoped is None else descoped
    seen_names = {} if seen_names is None else seen_names
    source = path.read_text()
    tree = ast.parse(source, filename=rel)
    lines = source.splitlines()
    out: List[Violation] = []
    _check_forbidden_idioms(rel, tree, out)
    _check_dense_intermediate(rel, tree, lines, out)
    aliases = _module_aliases(tree)
    for node in ast.walk(tree):
        if _is_lowering_fn(node):
            _check_host_sync(rel, node, aliases, lines, out)
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                name = _register_op_name(dec)
                if name is None:
                    continue
                if name in descoped:
                    out.append(Violation(
                        rel, node.lineno, "PL003",
                        f"register_op({name!r}) collides with "
                        "op_coverage.DESCOPED — drop the stale rationale "
                        f"(currently: {descoped[name][:60]}...)"))
                prev = seen_names.setdefault(name, f"{rel}:{node.lineno}")
                if prev != f"{rel}:{node.lineno}":
                    out.append(Violation(
                        rel, node.lineno, "PL004",
                        f"op {name!r} registered twice (first at {prev})"))
                _check_return_contract(rel, node, name, out)
    return out


def lint_paths(paths: Sequence) -> List[Violation]:
    descoped = _load_descoped()
    seen: Dict[str, str] = {}
    out: List[Violation] = []
    for p in paths:
        out.extend(lint_file(p, descoped, seen))
    return out


def default_targets() -> List[Path]:
    return sorted(REPO_ROOT.glob(OPS_GLOB))


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv:
        targets = [Path(a) for a in argv]
        violations = lint_paths(targets)
        for p in targets:
            violations.extend(lint_raw_mutation(p))
        n_files = len(targets)
    else:
        ops_targets = default_targets()
        mt = mutation_targets()
        violations = lint_paths(ops_targets)
        for p in mt:
            violations.extend(lint_raw_mutation(p))
        n_files = len(set(ops_targets) | set(mt))
    for v in violations:
        print(v)
    print(f"proglint: {n_files} file(s), {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
