"""memcheck — CLI front-end for the static peak-HBM verifier.

The third static-analysis tier (``static/memcheck.py``) prices a Program
× ShardingPlan pairing in bytes before anything traces or compiles:
per-device resident state, feed/fetch footprint, and the transient
high-water from sub-block-aware buffer lifetimes, decomposed the same
way ``aot.memory_analysis()`` reports it (args / out / temp) so the
prediction is directly comparable to what XLA later allocates.  MC001
(over capacity) is the only error; MC002–MC007 are advisory (missed
donation, dense embedding gradients, ZeRO opportunity, dead state, the
serving-ladder bound, embedding-capacity drops).

Usage::

    python -m tools.memcheck                     # demo fc tower, text
    python -m tools.memcheck --timeline          # per-op high-water bars
    python -m tools.memcheck --format json
    python -m tools.memcheck --capacity-gb 0.001 # force an MC001 verdict
    python -m tools.memcheck --selfcheck         # CI probe (rides tier-1)

There is no stable serialized Program format to load from disk yet, so
the CLI runs against the same built-in demo tower as ``tools.shardcheck``
under the current mesh.  ``--capacity-gb`` overrides the detected HBM
capacity (the ``memcheck_capacity_gb`` flag does the same for embedded
use); ``--selfcheck`` asserts the demo prices to a sane, internally
consistent estimate, that an impossible capacity yields MC001 (and a
generous one does not), and that the timeline peak matches the reported
peak — non-zero exit on any deviation.
"""
from __future__ import annotations

import argparse
import json
import sys


def _build_demo():
    from tools.shardcheck import _build_demo as build

    return build()


def _demo_report(capacity_bytes=None, timeline=False):
    """(MemReport, MemEstimate) for the demo tower under the current
    mesh's default data-parallel plan."""
    from paddle_tpu.parallel import mesh as M
    from paddle_tpu.parallel.sharding import ShardingPlan
    from paddle_tpu.static.memcheck import verify_memory

    program, _startup, feed_shapes = _build_demo()
    mesh = M.current_mesh()
    plan = ShardingPlan(mesh=mesh) if getattr(mesh, "size", 1) > 1 else None
    report = verify_memory(program, plan, feeds=feed_shapes,
                           capacity_bytes=capacity_bytes)
    return report


def selfcheck() -> int:
    """Price the demo tower; assert the estimate is sane and the MC001
    gate flips with capacity.  Rides tier-1 via subprocess."""
    report = _demo_report()
    est = report.mem
    if est is None:
        print("memcheck selfcheck: no estimate produced:\n"
              + report.render(), file=sys.stderr)
        return 1
    if est.peak_bytes <= 0 or est.args_bytes <= 0:
        print(f"memcheck selfcheck: degenerate estimate "
              f"(peak={est.peak_bytes}, args={est.args_bytes})",
              file=sys.stderr)
        return 1
    if not est.timeline:
        print("memcheck selfcheck: empty per-op timeline", file=sys.stderr)
        return 1
    high = max(b for _i, _t, b in est.timeline)
    if high > est.peak_bytes:
        print(f"memcheck selfcheck: timeline high-water {high} exceeds "
              f"reported peak {est.peak_bytes}", file=sys.stderr)
        return 1
    if report.errors:
        print("memcheck selfcheck: demo tower over capacity?!:\n"
              + report.render(), file=sys.stderr)
        return 1

    # the gate must flip: 1 KiB capacity -> MC001, 1 TiB -> clean
    tight = _demo_report(capacity_bytes=1024)
    if "MC001" not in {d.code for d in tight.diagnostics}:
        print("memcheck selfcheck: 1 KiB capacity did not raise MC001:\n"
              + tight.render(), file=sys.stderr)
        return 1
    roomy = _demo_report(capacity_bytes=1 << 40)
    if any(d.code == "MC001" for d in roomy.diagnostics):
        print("memcheck selfcheck: MC001 under a 1 TiB capacity",
              file=sys.stderr)
        return 1

    print(f"priced demo tower: peak {est.peak_bytes} bytes "
          f"(args {est.args_bytes} / out {est.out_bytes} / "
          f"temp {est.temp_bytes}) across {len(est.timeline)} ops; "
          f"MC001 gate flips with capacity")
    print("memcheck selfcheck: OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.memcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--timeline", action="store_true",
                        help="render the per-op high-water timeline")
    parser.add_argument("--capacity-gb", type=float, default=None,
                        help="override the detected per-device HBM "
                        "capacity (GiB); MC001 fires when the predicted "
                        "peak exceeds it")
    parser.add_argument("--selfcheck", action="store_true",
                        help="CI probe: assert a sane estimate and the "
                        "MC001 gate on the built-in demo")
    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    capacity = (None if args.capacity_gb is None
                else int(args.capacity_gb * (1 << 30)))
    report = _demo_report(capacity_bytes=capacity)

    if args.format == "json":
        payload = {
            "diagnostics": [
                {"code": d.code, "severity": d.severity,
                 "message": d.message, "var": d.var, "hint": d.hint}
                for d in report.diagnostics],
            "mem": report.mem.to_dict() if report.mem else None,
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(report.render())
        if args.timeline and report.mem is not None:
            print(report.mem.render(timeline=True))
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())
