"""shardcheck — CLI front-end for the two-tier static verifier.

Tier one (``static/analysis.py``) checks a Program in isolation
(PV001–PV010: dataflow, registry, structure, symbolic shape/dtype flow);
tier two (``static/shardcheck.py``) checks a Program × ShardingPlan
pairing (SC001–SC009: feed divisibility, mesh-axis validity, state
placement, donation aliasing, comm_quantize applicability, sub-block aval
consistency, ZeRO conflicts, predicted collectives) and produces the
static communication estimate.

Usage::

    python -m tools.shardcheck                  # demo program+plan, text
    python -m tools.shardcheck --format json
    python -m tools.shardcheck --coverage       # shape-rule coverage report
    python -m tools.shardcheck --selfcheck      # CI probe (rides tier-1)

There is no stable serialized Program format to load from disk yet, so
the CLI runs against a built-in demo: a small fc tower under a dp mesh
plan.  ``--misconfigured`` swaps in a deliberately broken plan (typo'd
axis, indivisible feed, donated feed-state alias, undersized quantization
bucket) so the diagnostic rendering can be eyeballed; ``--selfcheck``
asserts the broken plan yields exactly the expected SC codes and the
clean plan none, then prints ``shardcheck selfcheck: OK``.
"""
from __future__ import annotations

import argparse
import json
import sys


def _build_demo():
    """(program, startup, feed_shapes) for a small fc regression tower."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = L.data("x", [32])
        y = L.data("y", [1])
        h = L.fc(x, 64, act="relu")
        h = L.fc(h, 64, act="relu")
        pred = L.fc(h, 1)
        loss = L.mean(L.square_error_cost(pred, y))
        static.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, {"x": (16, 32), "y": (16, 1)}


def _clean_plan(mesh):
    from paddle_tpu.parallel.sharding import ShardingPlan

    return ShardingPlan(mesh=mesh, comm_quantize="int8")


def _broken_plan(mesh):
    """A plan seeded with misconfigurations the verifier must catch."""
    import re

    from paddle_tpu.parallel.sharding import ShardingPlan, ShardingRules

    rules = ShardingRules()
    # bypass add()'s eager validation the way stale pickled/config rules do
    rules.rules.append((re.compile(r"param_\d+"), ("dq", None)))
    return ShardingPlan(
        mesh=mesh, rules=rules,
        annotations={"param_0": ("dp", "dp", "dp"), "paramX_7": ("dp",)},
        zero_stage=3, comm_quantize="int8", comm_block_size=4096,
        comm_buffer_mb=0.001)


def _report(program, plan, feed_shapes, bucket_edges=None):
    from paddle_tpu.static.shardcheck import verify_plan

    return verify_plan(program, plan, feed_shapes=feed_shapes,
                       bucket_edges=bucket_edges)


def _coverage() -> dict:
    from paddle_tpu.static.analysis import shape_rule_coverage

    return shape_rule_coverage()


def _render_coverage(cov: dict) -> str:
    lines = [
        f"registered ops:        {cov['registered']}",
        f"inference rules:       {cov['inference_rules']}",
        f"plausibility checkers: {cov['plausibility_checkers']}",
        f"covered (either):      {cov['covered']} "
        f"({100.0 * cov['coverage']:.1f}%)",
    ]
    if cov["uncovered"]:
        lines.append("uncovered: " + ", ".join(cov["uncovered"][:40])
                     + (" ..." if len(cov["uncovered"]) > 40 else ""))
    return "\n".join(lines)


def selfcheck() -> int:
    """Build the demo under both plans; assert the broken one yields the
    expected SC codes, the clean one none, and the coverage report holds a
    floor.  Non-zero exit on any deviation — rides tier-1 via subprocess."""
    from paddle_tpu.parallel import mesh as M

    program, _startup, feed_shapes = _build_demo()
    mesh = M.current_mesh()          # all devices on dp

    clean = _report(program, _clean_plan(mesh), feed_shapes)
    if clean.errors:
        print("shardcheck selfcheck: clean plan produced errors:\n"
              + clean.render(), file=sys.stderr)
        return 1

    broken = _report(program, _broken_plan(mesh),
                     dict(feed_shapes, x=(10, 32), y=(10, 1)),
                     bucket_edges=(1, 2, 4))
    got = {d.code for d in broken.diagnostics}
    want = {"SC002", "SC003", "SC005"}
    n = mesh.size if hasattr(mesh, "size") else 1
    if n > 1:
        want |= {"SC001"}          # batch 10 does not divide the dp world
    missing = want - got
    if missing:
        print(f"shardcheck selfcheck: expected codes {sorted(want)}, "
              f"missing {sorted(missing)}; got {sorted(got)}:\n"
              + broken.render(), file=sys.stderr)
        return 1

    cov = _coverage()
    if cov["coverage"] < 0.65:
        print(f"shardcheck selfcheck: shape-rule coverage regressed to "
              f"{cov['coverage']:.2%}", file=sys.stderr)
        return 1

    est = clean.comm
    if est is None or not est.buckets or est.allreduce_bytes < 0:
        print("shardcheck selfcheck: comm estimate missing/empty",
              file=sys.stderr)
        return 1

    print(f"checked demo program under clean+broken plans; "
          f"{len(broken.diagnostics)} findings on broken, "
          f"coverage {cov['coverage']:.1%}")
    print("shardcheck selfcheck: OK")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.shardcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--misconfigured", action="store_true",
                        help="use the deliberately broken demo plan")
    parser.add_argument("--bucket-edges", default=None,
                        help="comma-separated serving bucket ladder to "
                        "check feeds against (e.g. 1,2,4,8)")
    parser.add_argument("--coverage", action="store_true",
                        help="print the shape-inference coverage report "
                        "and exit")
    parser.add_argument("--selfcheck", action="store_true",
                        help="CI probe: assert expected diagnostics on the "
                        "built-in demo")
    args = parser.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    if args.coverage:
        cov = _coverage()
        if args.format == "json":
            print(json.dumps(cov, indent=2, sort_keys=True))
        else:
            print(_render_coverage(cov))
        return 0

    from paddle_tpu.parallel import mesh as M

    program, _startup, feed_shapes = _build_demo()
    mesh = M.current_mesh()
    plan = _broken_plan(mesh) if args.misconfigured else _clean_plan(mesh)
    edges = None
    if args.bucket_edges:
        edges = tuple(int(e) for e in args.bucket_edges.split(","))
    report = _report(program, plan, feed_shapes, bucket_edges=edges)

    if args.format == "json":
        payload = {
            "diagnostics": [
                {"code": d.code, "severity": d.severity,
                 "message": d.message, "block": d.block,
                 "op_index": d.op_index, "op_type": d.op_type,
                 "var": d.var, "hint": d.hint}
                for d in report.diagnostics],
            "comm": report.comm.to_dict() if report.comm else None,
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(report.render())
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())
