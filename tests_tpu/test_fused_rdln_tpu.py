"""TPU-only validation of the fused residual+dropout+LayerNorm kernel's
hardware-PRNG dropout (the CPU suite runs interpret mode with the hash
mask; run `pytest tests_tpu/` on a TPU host)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import layer_norm as fln

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="hardware-PRNG dropout only lowers on real TPUs")

N, D = 2048, 768
RATE = 0.3


def _ref_ln(h, w, b, eps=1e-5):
    hf = h.astype(jnp.float32)
    m = hf.mean(-1, keepdims=True)
    v = hf.var(-1, keepdims=True)
    return (((hf - m) / jnp.sqrt(v + eps)) * w + b).astype(h.dtype)


def test_rate0_matches_composition_bf16():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.bfloat16)
    res = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(1, 0.1, (D,)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (D,)), jnp.float32)
    out = fln.fused_residual_dropout_layer_norm(x, res, w, b, 0.0)
    ref = _ref_ln(res.astype(jnp.float32) + x.astype(jnp.float32), w, b)
    assert float(jnp.abs(out - ref).max()) < 2e-2  # one bf16 ulp at |2|


def test_hw_dropout_mask_replay_between_fwd_and_bwd():
    """Gradients w.r.t. x must be zero exactly on positions the forward
    dropped: the backward kernel replays the identical hardware-PRNG
    stream (per-tile reseed), not a fresh draw."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)
    res = jnp.zeros((N, D), jnp.float32)
    w = jnp.ones((D,), jnp.float32)
    b = jnp.zeros((D,), jnp.float32)
    seed = jnp.asarray([99], jnp.int32)

    f = lambda x_: fln.fused_residual_dropout_layer_norm(
        x_, res, w, b, RATE, seed=seed)
    o1, o2 = f(x), f(x)
    assert np.array_equal(np.asarray(o1), np.asarray(o2))
    dx = jax.grad(lambda x_: (f(x_) ** 2).sum())(x)
    drop_frac = float((np.asarray(dx) == 0).mean())
    assert abs(drop_frac - RATE) < 0.02, drop_frac
    # the same seed with rate 0 has no zeros (mask is really the cause)
    dx0 = jax.grad(lambda x_: (fln.fused_residual_dropout_layer_norm(
        x_, res, w, b, 0.0, seed=seed) ** 2).sum())(x)
    assert float((np.asarray(dx0) == 0).mean()) < 0.001
