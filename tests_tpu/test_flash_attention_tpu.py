"""TPU-only validation of the hardware-PRNG dropout path in the Pallas
flash-attention kernel (tests/conftest.py forces the CPU interpret backend,
where `_keep_mask` routes to the murmur hash — so the production TPU path
needs its own gate; run `pytest tests_tpu/` from an
environment with a real TPU and no JAX_PLATFORMS override).

The load-bearing claim under test: per-(seed, bh, q_block, k_block) tile
reseeding makes the hardware PRNG stream replayable across the forward,
dK/dV, and dQ kernels even though they visit S-matrix tiles in different
orders.  We extract the actual keep mask with a dump kernel that uses the
identical seeding, recompute reference attention + grads WITH that exact
mask, and require the kernel's outputs/grads to match.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="hardware-PRNG dropout only lowers on real TPUs")

B, H, S, D = 2, 3, 512, 64
RATE = 0.1


def _qkv():
    rng = np.random.default_rng(0)
    return tuple(jnp.asarray(rng.normal(0, 1, (B, H, S, D)), jnp.float32)
                 for _ in range(3))


def _dump_mask(seed, bq=512, bk=512):
    from jax.experimental import pallas as pl

    def kernel(seed_ref, out_ref):
        bh_idx = pl.program_id(0)
        qi = pl.program_id(1)

        def body(kv, _):
            keep = fa._dropout_keep_hw(seed_ref[0], bh_idx, qi, kv,
                                       (bq, bk), RATE)
            out_ref[0, :, pl.dslice(kv * bk, bk)] = keep
            return 0

        jax.lax.fori_loop(0, S // bk, body, 0)

    mask = pl.pallas_call(
        kernel, grid=(B * H, S // bq),
        in_specs=[pl.BlockSpec(memory_space=fa._smem())],
        out_specs=pl.BlockSpec((1, bq, S), lambda bh_i, i: (bh_i, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, S), jnp.bool_),
    )(seed)
    return np.asarray(mask).reshape(B, H, S, S)


def _ref_attn(q, k, v, mask):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / (D ** 0.5)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask, p / (1 - RATE), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def test_hw_dropout_deterministic_and_rate():
    q, k, v = _qkv()
    seed = jnp.asarray([1234], jnp.int32)
    o1 = fa.flash_attention(q, k, v, dropout_rate=RATE, seed=seed)
    o2 = fa.flash_attention(q, k, v, dropout_rate=RATE, seed=seed)
    assert np.array_equal(np.asarray(o1), np.asarray(o2))
    mask = _dump_mask(seed)
    assert abs(mask.mean() - (1 - RATE)) < 0.01


def test_hw_dropout_fwd_bwd_mask_consistency():
    q, k, v = _qkv()
    seed = jnp.asarray([1234], jnp.int32)
    mask = _dump_mask(seed)

    out = fa.flash_attention(q, k, v, dropout_rate=RATE, seed=seed)
    ref = _ref_attn(q, k, v, mask)
    assert float(jnp.abs(out - ref).max()) < 1e-2  # TPU default dot precision

    g_kernel = jax.grad(lambda t: (fa.flash_attention(
        t[0], t[1], t[2], dropout_rate=RATE, seed=seed) ** 2).sum())((q, k, v))
    g_ref = jax.grad(lambda t: (_ref_attn(t[0], t[1], t[2], mask) ** 2).sum())(
        (q, k, v))
    for name, a, b in zip("qkv", g_kernel, g_ref):
        diff = float(jnp.abs(a - b).max())
        mag = float(jnp.abs(b).max())
        assert diff < 1e-2 * max(mag, 1.0), (name, diff, mag)
