"""TPU-only gradient validation of the PACKED-layout flash attention
against the jnp reference (run `pytest tests_tpu/` on a TPU host).

Methodology note (learned the hard way): when the loss packs (b, h, s, d)
inputs internally, jax.grad already returns cotangents in the ORIGINAL
(b, h, s, d) space — do NOT "unpack" them again.  A harness that did
produced bit-stable garbage comparisons that perfectly impersonated a
Mosaic miscompile across five kernel rewrites.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import scaled_dot_product_attention as sdpa
from paddle_tpu.ops.pallas.flash_attention_packed import flash_attention_packed

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="validates the real-TPU lowering of the packed kernel")


@pytest.mark.parametrize("b,h,s,d,blocks", [
    (2, 4, 512, 64, None),     # head pairs
    (8, 12, 512, 64, None),    # flagship shape (batch slice)
    (2, 2, 512, 128, None),    # single 128-wide heads
    (2, 4, 1024, 64, 256),     # multi-block
])
def test_packed_grads_match_jnp_reference(b, h, s, d, blocks):
    rng = np.random.default_rng(0)
    q4, k4, v4 = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)), jnp.float32)
                  for _ in range(3))

    def pack(t):
        return jnp.moveaxis(t, 1, 2).reshape(b, s, h * d)

    kw = {} if blocks is None else {"block_q": blocks, "block_k": blocks}
    g_ref = jax.grad(lambda t: (sdpa(t[0], t[1], t[2], training=False) ** 2
                                ).sum())((q4, k4, v4))
    g_pk = jax.grad(lambda t: (flash_attention_packed(
        pack(t[0]), pack(t[1]), pack(t[2]), h, **kw) ** 2).sum())(
        (q4, k4, v4))
    # grads are w.r.t. the (b, h, s, d) inputs — compare DIRECTLY
    for name, a, r in zip("qkv", g_pk, g_ref):
        rel = float(jnp.abs(a - r).max() / jnp.abs(r).max())
        assert rel < 0.02, (name, rel)  # TPU default matmul precision
