"""TPU-gated numeric checks closing the round-4 coverage gap (VERDICT
weak #4): the Pallas LayerNorm forward AND backward on the chip, the fused
sublayer epilogue's gradients at a second shape, one ResNet bottleneck
block forward/backward against an fp32 oracle, and a long-context (s2048)
flash-attention training step.  Everything else validates on the CPU
backend, which has not historically caught TPU-only layout/precision bugs
(the reference gates per-op tests on every place, op_test.py:948)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="on-device numeric checks need the real TPU backend")


def _ref_ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    m = xf.mean(-1, keepdims=True)
    v = xf.var(-1, keepdims=True)
    return ((xf - m) / jnp.sqrt(v + eps)) * w + b


def test_pallas_layer_norm_forward_and_backward_on_device():
    from paddle_tpu.ops.pallas import layer_norm as fln

    N, D = 1024, 768
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)
    w = jnp.asarray(rng.normal(1, 0.1, (D,)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (D,)), jnp.float32)
    dy = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)

    out = fln.fused_layer_norm(x, w, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref_ln(x, w, b)),
                               rtol=2e-2, atol=2e-3)

    def kernel_loss(x_, w_, b_):
        return jnp.sum(fln.fused_layer_norm(x_, w_, b_) * dy)

    def ref_loss(x_, w_, b_):
        return jnp.sum(_ref_ln(x_, w_, b_) * dy)

    gk = jax.grad(kernel_loss, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, e, name in zip(gk, gr, ("dx", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=3e-2, atol=5e-2,
            err_msg=f"LayerNorm backward {name} diverges on-device")


def test_fused_sublayer_epilogue_grads_second_shape():
    """r04 covered (2048, 768); pin a second, non-multiple-of-512 row
    count and wider feature dim so tile-edge paths get a device check."""
    from paddle_tpu.ops.pallas import layer_norm as fln

    N, D = 1536, 1024
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)
    res = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)
    w = jnp.asarray(rng.normal(1, 0.1, (D,)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (D,)), jnp.float32)
    dy = jnp.asarray(rng.normal(0, 1, (N, D)), jnp.float32)

    def kernel_loss(x_, res_, w_, b_):
        return jnp.sum(fln.fused_residual_dropout_layer_norm(
            x_, res_, w_, b_, 0.0) * dy)

    def ref_loss(x_, res_, w_, b_):
        return jnp.sum(_ref_ln(x_ + res_, w_, b_) * dy)

    gk = jax.grad(kernel_loss, argnums=(0, 1, 2, 3))(x, res, w, b)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(x, res, w, b)
    for a, e, name in zip(gk, gr, ("dx", "dres", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=3e-2, atol=5e-2,
            err_msg=f"fused epilogue {name} diverges at (1536, 1024)")


def test_resnet_bottleneck_block_fwd_bwd_vs_fp32_oracle():
    """One BottleneckBlock training step on-device in bf16 vs the same
    math in fp32 — catches TPU conv layout/precision regressions the CPU
    suite cannot see."""
    from paddle_tpu import autograd
    from paddle_tpu.autograd import parameters_dict
    from paddle_tpu.vision.models.resnet import BottleneckBlock

    rng = np.random.default_rng(2)
    blk = BottleneckBlock(64, 16)
    blk.train()
    params = parameters_dict(blk)
    x = rng.normal(0, 1, (4, 64, 16, 16)).astype(np.float32)

    def loss(p, dtype):
        cast = jax.tree_util.tree_map(
            lambda a: a.astype(dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
        out = autograd.functional_call(blk, cast,
                                       (jnp.asarray(x, dtype),))
        return jnp.mean(out.astype(jnp.float32) ** 2)

    l16, g16 = jax.value_and_grad(lambda p: loss(p, jnp.bfloat16))(params)
    l32, g32 = jax.value_and_grad(lambda p: loss(p, jnp.float32))(params)
    np.testing.assert_allclose(float(l16), float(l32), rtol=5e-2)
    flat16 = jax.tree_util.tree_leaves(g16)
    flat32 = jax.tree_util.tree_leaves(g32)
    for a, e in zip(flat16, flat32):
        denom = float(jnp.abs(e).max()) + 1e-6
        assert float(jnp.abs(a - e).max()) / denom < 0.15, \
            "bf16 block gradient diverges from fp32 oracle on-device"


def test_long_context_s2048_flash_training_step():
    """One s2048 flash-attention step with gradients on the chip: the
    long-context path (BASELINE.md s2048 numbers) gets an on-device
    numeric gate, not just a throughput entry."""
    from paddle_tpu.ops.pallas import flash_attention as fa

    B, H, S, D = 1, 4, 2048, 64
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (B, H, S, D)), jnp.float32)
               for _ in range(3))
    dy = jnp.asarray(rng.normal(0, 1, (B, H, S, D)), jnp.float32)

    def ref(q_, k_, v_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v_)

    out_k = fa.flash_attention(q, k, v, causal=True)
    out_r = ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=2e-2, atol=2e-2)

    gk = jax.grad(lambda q_, k_, v_: jnp.sum(
        fa.flash_attention(q_, k_, v_, causal=True) * dy),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q_, k_, v_: jnp.sum(ref(q_, k_, v_) * dy),
                  argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(gk, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=5e-2, atol=5e-2,
            err_msg=f"s2048 flash {name} diverges on-device")
