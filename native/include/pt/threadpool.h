// Fixed-size worker pool.
//
// Reference parity: paddle/fluid/framework/threadpool.h (ThreadPool::Run)
// — used here by the data-feed engine for parallel file parsing and async
// batch assembly. Kept deliberately simple: futures via std::packaged_task.
#pragma once

#include <functional>
#include <future>
#include <queue>
#include <thread>
#include <vector>

namespace pt {

class ThreadPool {
 public:
  explicit ThreadPool(size_t n) : stop_(false) {
    for (size_t i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lk(mu_);
            cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
            if (stop_ && tasks_.empty()) return;
            task = std::move(tasks_.front());
            tasks_.pop();
          }
          task();
        }
      });
    }
  }

  template <typename F>
  std::future<void> Run(F&& f) {
    auto task = std::make_shared<std::packaged_task<void()>>(std::forward<F>(f));
    std::future<void> fut = task->get_future();
    {
      std::lock_guard<std::mutex> lk(mu_);
      tasks_.emplace([task] { (*task)(); });
    }
    cv_.notify_one();
    return fut;
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

}  // namespace pt
