// Bounded blocking MPMC channel.
//
// Reference parity: paddle/fluid/framework/channel.h (Go-style channel used
// by the DataFeed/Dataset pipeline) — rebuilt minimal and TPU-host oriented:
// it only ever carries host-side sample/batch structs, never device memory
// (XLA owns device memory; SURVEY.md L0b TPU mapping).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <utility>

namespace pt {

template <typename T>
class Channel {
 public:
  explicit Channel(size_t capacity = 0) : capacity_(capacity), closed_(false) {}

  // Returns false if the channel is closed.
  bool Put(T&& item) {
    std::unique_lock<std::mutex> lk(mu_);
    send_cv_.wait(lk, [&] {
      return closed_ || capacity_ == 0 || buf_.size() < capacity_;
    });
    if (closed_) return false;
    buf_.push_back(std::move(item));
    recv_cv_.notify_one();
    return true;
  }

  // Returns false when the channel is closed AND drained.
  bool Get(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    recv_cv_.wait(lk, [&] { return closed_ || !buf_.empty(); });
    if (buf_.empty()) return false;
    *out = std::move(buf_.front());
    buf_.pop_front();
    send_cv_.notify_one();
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    send_cv_.notify_all();
    recv_cv_.notify_all();
  }

  void Reopen() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = false;
    buf_.clear();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return buf_.size();
  }

 private:
  size_t capacity_;
  bool closed_;
  std::deque<T> buf_;
  std::mutex mu_;
  std::condition_variable send_cv_, recv_cv_;
};

}  // namespace pt
