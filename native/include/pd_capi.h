/* C inference/training API for paddle_tpu.
 *
 * Reference parity: paddle/fluid/inference/capi/ (pd_predictor.cc,
 * pd_config.cc) — a C surface over the predictor so C programs (and FFIs:
 * the reference's Go binding go/paddle/predictor.go wraps exactly this) can
 * run saved models.  TPU-native design: the compute engine is JAX/XLA in a
 * Python runtime, so this library is a zero-dependency CLIENT that spawns
 * the paddle_tpu.inference.capi_worker service as a child process and
 * exchanges tensors over a length-prefixed pipe protocol; the model still
 * executes on the real backend (TPU or CPU).  One handle serves both the
 * inference dirs written by save_inference_model() and the trainable
 * prefixes written by static.save() — running a program that contains
 * backward+optimizer ops through PD_PredictorRun IS a training step
 * (the reference's fluid/train/demo contract).
 */
#ifndef PD_CAPI_H_
#define PD_CAPI_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PD_FLOAT32 = 0,
  PD_INT32 = 1,
  PD_INT64 = 2,
  PD_FLOAT64 = 3,
  PD_UINT8 = 4,
  PD_BOOL = 5,
} PD_DataType;

#define PD_MAX_NAME 128
#define PD_MAX_RANK 8

typedef struct {
  char name[PD_MAX_NAME];
  int dtype;                 /* PD_DataType */
  int ndim;
  long long shape[PD_MAX_RANK];
  void* data;                /* owned by caller for inputs; by the library
                                for outputs (free with PD_TensorsFree) */
} PD_Tensor;

typedef struct PD_Predictor PD_Predictor;

/* model_path: a save_inference_model directory or a static.save prefix.
 * python_exe: interpreter to run the worker with (NULL = "python3").
 * Returns NULL on failure. */
PD_Predictor* PD_PredictorCreate(const char* model_path,
                                 const char* python_exe);

/* IN-PROCESS variant (the reference's AnalysisPredictor embedding,
 * inference/capi/pd_predictor.cc): embeds CPython via dlopen'd libpython
 * (override the library name with PD_LIBPYTHON) and executes the model in
 * THIS process — no worker fork, no pipe.  When the library is loaded
 * from a live Python process (e.g. via ctypes) the existing interpreter
 * is reused.  Same wire semantics as PD_PredictorCreate. */
PD_Predictor* PD_PredictorCreateInProcess(const char* model_path);

/* Runs one feed->fetch round trip.  outputs/n_outputs are filled with
 * library-owned tensors (release with PD_TensorsFree).  Returns 0 on
 * success, nonzero on failure (PD_GetLastError describes it). */
int PD_PredictorRun(PD_Predictor* pred, const PD_Tensor* inputs, int n_inputs,
                    PD_Tensor** outputs, int* n_outputs);

void PD_TensorsFree(PD_Tensor* tensors, int n);
void PD_PredictorDestroy(PD_Predictor* pred);
const char* PD_GetLastError(void);

#ifdef __cplusplus
}
#endif
#endif /* PD_CAPI_H_ */
