/* Pure-C training from a saved program (N38; ref paddle/fluid/train/demo/
 * demo_trainer.cc + test_train_recognize_digits.cc: load a program saved by
 * the python front end, run train steps from C++, watch the loss drop).
 *
 * Usage: train_demo <model_prefix> <steps>
 *   model_prefix: written by paddle_tpu.static.save() on a program that
 *   CONTAINS backward + optimizer ops and fetches the loss.
 * Prints one loss per step; exits 0 iff the final loss < first loss.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pd_capi.h"

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_prefix> <steps>\n", argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  int steps = atoi(argv[2]);

  PD_Predictor* pred = PD_PredictorCreate(prefix, NULL);
  if (!pred) {
    fprintf(stderr, "create failed: %s\n", PD_GetLastError());
    return 1;
  }

  /* toy linear-regression batch: y = 2x + 1 with 13 features summed */
  enum { B = 16, D = 13 };
  static float xbuf[B * D], ybuf[B];
  unsigned seed = 7;
  double first = -1.0, last = -1.0;

  for (int step = 0; step < steps; ++step) {
    for (int i = 0; i < B; ++i) {
      float s = 0.f;
      for (int j = 0; j < D; ++j) {
        seed = seed * 1103515245u + 12345u;
        float v = (float)((seed >> 16) & 0x7fff) / 32768.0f;
        xbuf[i * D + j] = v;
        s += v;
      }
      ybuf[i] = 2.0f * s + 1.0f;
    }
    PD_Tensor inputs[2];
    memset(inputs, 0, sizeof(inputs));
    snprintf(inputs[0].name, PD_MAX_NAME, "x");
    inputs[0].dtype = PD_FLOAT32;
    inputs[0].ndim = 2;
    inputs[0].shape[0] = B;
    inputs[0].shape[1] = D;
    inputs[0].data = xbuf;
    snprintf(inputs[1].name, PD_MAX_NAME, "y");
    inputs[1].dtype = PD_FLOAT32;
    inputs[1].ndim = 2;
    inputs[1].shape[0] = B;
    inputs[1].shape[1] = 1;
    inputs[1].data = ybuf;

    PD_Tensor* outputs = NULL;
    int n_out = 0;
    if (PD_PredictorRun(pred, inputs, 2, &outputs, &n_out) != 0) {
      fprintf(stderr, "run failed: %s\n", PD_GetLastError());
      PD_PredictorDestroy(pred);
      return 1;
    }
    if (n_out < 1 || outputs[0].dtype != PD_FLOAT32) {
      fprintf(stderr, "expected a float32 loss fetch\n");
      return 1;
    }
    last = ((float*)outputs[0].data)[0];
    if (step == 0) first = last;
    printf("step %d loss %.6f\n", step, last);
    PD_TensorsFree(outputs, n_out);
  }
  PD_PredictorDestroy(pred);
  if (!(last < first)) {
    fprintf(stderr, "loss did not decrease: first=%f last=%f\n", first, last);
    return 1;
  }
  return 0;
}
