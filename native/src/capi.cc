// C inference/training API: pipe-protocol client for the capi_worker
// Executor service.  See native/include/pd_capi.h for the design note
// (ref paddle/fluid/inference/capi/pd_predictor.cc).
#include "pd_capi.h"

#include <signal.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void SetError(const std::string& msg) { g_last_error = msg; }

size_t DtypeSize(int dtype) {
  switch (dtype) {
    case PD_FLOAT32: return 4;
    case PD_INT32: return 4;
    case PD_INT64: return 8;
    case PD_FLOAT64: return 8;
    case PD_UINT8: return 1;
    case PD_BOOL: return 1;
    default: return 0;
  }
}

long long Numel(const PD_Tensor& t) {
  long long n = 1;
  for (int i = 0; i < t.ndim; ++i) n *= t.shape[i];
  return n;
}

bool WriteAll(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t w = write(fd, p, len);
    if (w <= 0) return false;
    p += w;
    len -= static_cast<size_t>(w);
  }
  return true;
}

bool ReadAll(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t r = read(fd, p, len);
    if (r <= 0) return false;
    p += r;
    len -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

struct PD_Predictor {
  pid_t pid = -1;
  int to_worker = -1;    // write end
  int from_worker = -1;  // read end
};

extern "C" {

PD_Predictor* PD_PredictorCreate(const char* model_path,
                                 const char* python_exe) {
  if (model_path == nullptr) {
    SetError("model_path is NULL");
    return nullptr;
  }
  const char* py = python_exe ? python_exe : "python3";
  int in_pipe[2], out_pipe[2];
  if (pipe(in_pipe) != 0) {
    SetError("pipe() failed");
    return nullptr;
  }
  if (pipe(out_pipe) != 0) {
    SetError("pipe() failed");
    close(in_pipe[0]); close(in_pipe[1]);
    return nullptr;
  }
  pid_t pid = fork();
  if (pid < 0) {
    SetError("fork() failed");
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    return nullptr;
  }
  if (pid == 0) {
    // child: stdin <- in_pipe[0], stdout -> out_pipe[1]
    dup2(in_pipe[0], STDIN_FILENO);
    dup2(out_pipe[1], STDOUT_FILENO);
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    execlp(py, py, "-m", "paddle_tpu.inference.capi_worker", model_path,
           static_cast<char*>(nullptr));
    std::fprintf(stderr, "pd_capi: execlp(%s) failed\n", py);
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  auto* pred = new PD_Predictor;
  pred->pid = pid;
  pred->to_worker = in_pipe[1];
  pred->from_worker = out_pipe[0];
  char ready[4];
  if (!ReadAll(pred->from_worker, ready, 4) ||
      std::memcmp(ready, "PDOK", 4) != 0) {
    SetError("worker failed to start (is paddle_tpu importable by " +
             std::string(py) + "?)");
    PD_PredictorDestroy(pred);
    return nullptr;
  }
  return pred;
}

int PD_PredictorRun(PD_Predictor* pred, const PD_Tensor* inputs, int n_inputs,
                    PD_Tensor** outputs, int* n_outputs) {
  if (!pred || pred->pid < 0) {
    SetError("invalid predictor");
    return -1;
  }
  int fd = pred->to_worker;
  if (!WriteAll(fd, "PDRQ", 4)) { SetError("write failed"); return -1; }
  int32_t n = n_inputs;
  WriteAll(fd, &n, 4);
  for (int i = 0; i < n_inputs; ++i) {
    const PD_Tensor& t = inputs[i];
    int32_t name_len = static_cast<int32_t>(std::strlen(t.name));
    WriteAll(fd, &name_len, 4);
    WriteAll(fd, t.name, name_len);
    int32_t dtype = t.dtype, ndim = t.ndim;
    WriteAll(fd, &dtype, 4);
    WriteAll(fd, &ndim, 4);
    for (int d = 0; d < t.ndim; ++d) {
      int64_t dim = t.shape[d];
      WriteAll(fd, &dim, 8);
    }
    if (!WriteAll(fd, t.data, Numel(t) * DtypeSize(t.dtype))) {
      SetError("tensor write failed");
      return -1;
    }
  }
  char magic[4];
  if (!ReadAll(pred->from_worker, magic, 4)) {
    SetError("worker closed the pipe");
    return -1;
  }
  if (std::memcmp(magic, "PDER", 4) == 0) {
    int32_t len = 0;
    ReadAll(pred->from_worker, &len, 4);
    std::string msg(len, '\0');
    ReadAll(pred->from_worker, msg.data(), len);
    SetError("worker error: " + msg);
    return -2;
  }
  if (std::memcmp(magic, "PDRS", 4) != 0) {
    SetError("bad response magic");
    return -1;
  }
  int32_t n_out = 0;
  if (!ReadAll(pred->from_worker, &n_out, 4)) {
    SetError("truncated response");
    return -1;
  }
  if (n_out < 0 || n_out > 4096) {
    SetError("implausible output count (protocol desync?)");
    return -1;
  }
  auto* outs = static_cast<PD_Tensor*>(std::calloc(n_out, sizeof(PD_Tensor)));
  for (int i = 0; i < n_out; ++i) {
    PD_Tensor& t = outs[i];
    int32_t name_len = 0;
    if (!ReadAll(pred->from_worker, &name_len, 4) || name_len < 0 ||
        name_len > 4096) {
      SetError("bad tensor name length");
      PD_TensorsFree(outs, i);
      return -1;
    }
    std::string name(name_len, '\0');
    if (!ReadAll(pred->from_worker, name.data(), name_len)) {
      SetError("truncated tensor name");
      PD_TensorsFree(outs, i);
      return -1;
    }
    std::snprintf(t.name, PD_MAX_NAME, "%s", name.c_str());
    int32_t dtype = 0, ndim = 0;
    if (!ReadAll(pred->from_worker, &dtype, 4) ||
        !ReadAll(pred->from_worker, &ndim, 4) || DtypeSize(dtype) == 0 ||
        ndim < 0 || ndim > PD_MAX_RANK) {
      SetError("bad tensor header (dtype/ndim out of range for pd_capi)");
      PD_TensorsFree(outs, i);
      return -1;
    }
    t.dtype = dtype;
    t.ndim = ndim;
    for (int d = 0; d < ndim; ++d) {
      int64_t dim = 0;
      if (!ReadAll(pred->from_worker, &dim, 8) || dim < 0) {
        SetError("bad tensor dim");
        PD_TensorsFree(outs, i);
        return -1;
      }
      t.shape[d] = dim;
    }
    size_t bytes = static_cast<size_t>(Numel(t)) * DtypeSize(t.dtype);
    t.data = std::malloc(bytes ? bytes : 1);
    if (!ReadAll(pred->from_worker, t.data, bytes)) {
      SetError("truncated tensor payload");
      PD_TensorsFree(outs, i + 1);
      return -1;
    }
  }
  *outputs = outs;
  *n_outputs = n_out;
  return 0;
}

void PD_TensorsFree(PD_Tensor* tensors, int n) {
  if (!tensors) return;
  for (int i = 0; i < n; ++i) std::free(tensors[i].data);
  std::free(tensors);
}

void PD_PredictorDestroy(PD_Predictor* pred) {
  if (!pred) return;
  if (pred->to_worker >= 0) close(pred->to_worker);
  if (pred->from_worker >= 0) close(pred->from_worker);
  if (pred->pid > 0) {
    int status = 0;
    // worker exits on stdin EOF; reap it (kill after a grace period is the
    // caller's job if it wants hard deadlines)
    waitpid(pred->pid, &status, 0);
  }
  delete pred;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

}  // extern "C"
