// C inference/training API: pipe-protocol client for the capi_worker
// Executor service.  See native/include/pd_capi.h for the design note
// (ref paddle/fluid/inference/capi/pd_predictor.cc).
#include "pd_capi.h"

#include <dlfcn.h>
#include <signal.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void SetError(const std::string& msg) { g_last_error = msg; }

size_t DtypeSize(int dtype) {
  switch (dtype) {
    case PD_FLOAT32: return 4;
    case PD_INT32: return 4;
    case PD_INT64: return 8;
    case PD_FLOAT64: return 8;
    case PD_UINT8: return 1;
    case PD_BOOL: return 1;
    default: return 0;
  }
}

long long Numel(const PD_Tensor& t) {
  long long n = 1;
  for (int i = 0; i < t.ndim; ++i) n *= t.shape[i];
  return n;
}

bool WriteAll(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t w = write(fd, p, len);
    if (w <= 0) return false;
    p += w;
    len -= static_cast<size_t>(w);
  }
  return true;
}

bool ReadAll(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t r = read(fd, p, len);
    if (r <= 0) return false;
    p += r;
    len -= static_cast<size_t>(r);
  }
  return true;
}

// -- embedded CPython (in-process transport) --------------------------------
// libpython is dlopen'd on demand so the library keeps zero link-time
// dependencies; only the stable C-API entry points below are used.
struct PyApi {
  int (*IsInitialized)();
  void (*InitializeEx)(int);
  int (*GILState_Ensure)();                       // PyGILState_STATE as int
  void (*GILState_Release)(int);
  void* (*Eval_SaveThread)();
  void* (*Import_ImportModule)(const char*);
  void* (*Unicode_FromString)(const char*);
  void* (*Long_FromLong)(long);
  long (*Long_AsLong)(void*);
  void* (*Bytes_FromStringAndSize)(const char*, ssize_t);
  int (*Bytes_AsStringAndSize)(void*, char**, ssize_t*);
  void* (*Object_CallMethodObjArgs)(void*, void*, ...);
  void (*Object_DecRef)(void*);  // Py_DecRef
  void* (*Err_Occurred)();
  void (*Err_Print)();
  bool ok = false;
};

PyApi g_py;
std::mutex g_py_mutex;

void* PySym(void* lib, const char* name) {
  void* s = dlsym(RTLD_DEFAULT, name);  // already-live interpreter first
  if (!s && lib) s = dlsym(lib, name);
  return s;
}

bool EnsurePython() {
  std::lock_guard<std::mutex> lock(g_py_mutex);
  if (g_py.ok) return true;
  void* lib = nullptr;
  if (!dlsym(RTLD_DEFAULT, "Py_IsInitialized")) {
    const char* cand[] = {getenv("PD_LIBPYTHON"),
                          "libpython3.14.so.1.0", "libpython3.14.so",
                          "libpython3.13.so.1.0", "libpython3.13.so",
                          "libpython3.12.so.1.0", "libpython3.12.so",
                          "libpython3.11.so.1.0", "libpython3.11.so",
                          "libpython3.10.so.1.0"};
    for (const char* c : cand) {
      if (!c) continue;  // PD_LIBPYTHON may be unset
      lib = dlopen(c, RTLD_NOW | RTLD_GLOBAL);
      if (lib) break;
    }
    if (!lib) {
      SetError("libpython not found (set PD_LIBPYTHON)");
      return false;
    }
  }
#define PD_SYM(field, name)                                            \
  g_py.field = reinterpret_cast<decltype(g_py.field)>(PySym(lib, name)); \
  if (!g_py.field) { SetError("missing python symbol " name); return false; }
  PD_SYM(IsInitialized, "Py_IsInitialized")
  PD_SYM(InitializeEx, "Py_InitializeEx")
  PD_SYM(GILState_Ensure, "PyGILState_Ensure")
  PD_SYM(GILState_Release, "PyGILState_Release")
  PD_SYM(Eval_SaveThread, "PyEval_SaveThread")
  PD_SYM(Import_ImportModule, "PyImport_ImportModule")
  PD_SYM(Unicode_FromString, "PyUnicode_FromString")
  PD_SYM(Long_FromLong, "PyLong_FromLong")
  PD_SYM(Long_AsLong, "PyLong_AsLong")
  PD_SYM(Bytes_FromStringAndSize, "PyBytes_FromStringAndSize")
  PD_SYM(Bytes_AsStringAndSize, "PyBytes_AsStringAndSize")
  PD_SYM(Object_CallMethodObjArgs, "PyObject_CallMethodObjArgs")
  PD_SYM(Object_DecRef, "Py_DecRef")
  PD_SYM(Err_Occurred, "PyErr_Occurred")
  PD_SYM(Err_Print, "PyErr_Print")
#undef PD_SYM
  if (!g_py.IsInitialized()) {
    g_py.InitializeEx(0);
    g_py.Eval_SaveThread();  // release the GIL: calls use GILState_Ensure
  }
  g_py.ok = true;
  return true;
}

// Serialize a PDRQ request through a put callback: the pipe transport
// streams straight to the fd (no payload copy), the in-process transport
// collects into a buffer.
using PutFn = std::function<bool(const void*, size_t)>;

bool SerializeRequest(const PD_Tensor* inputs, int n_inputs,
                      const PutFn& put) {
  if (!put("PDRQ", 4)) return false;
  int32_t n = n_inputs;
  if (!put(&n, 4)) return false;
  for (int i = 0; i < n_inputs; ++i) {
    const PD_Tensor& t = inputs[i];
    int32_t name_len = static_cast<int32_t>(std::strlen(t.name));
    if (!put(&name_len, 4) || !put(t.name, name_len)) return false;
    int32_t dtype = t.dtype, ndim = t.ndim;
    if (!put(&dtype, 4) || !put(&ndim, 4)) return false;
    for (int d = 0; d < t.ndim; ++d) {
      int64_t dim = t.shape[d];
      if (!put(&dim, 8)) return false;
    }
    if (!put(t.data, Numel(t) * DtypeSize(t.dtype))) return false;
  }
  return true;
}

// Parse a PDRS/PDER response through a read callback (fd or memory).
using ReadFn = std::function<bool(void*, size_t)>;

int ParseResponse(const ReadFn& rd, PD_Tensor** outputs, int* n_outputs) {
  char magic[4];
  if (!rd(magic, 4)) {
    SetError("truncated response");
    return -1;
  }
  if (std::memcmp(magic, "PDER", 4) == 0) {
    int32_t len = 0;
    if (!rd(&len, 4) || len < 0 || len > 65536) {
      SetError("worker error (malformed error frame)");
      return -2;
    }
    std::string msg(static_cast<size_t>(len), '\0');
    if (!rd(msg.data(), msg.size())) msg = "(truncated error message)";
    SetError("worker error: " + msg);
    return -2;
  }
  if (std::memcmp(magic, "PDRS", 4) != 0) {
    SetError("bad response magic");
    return -1;
  }
  int32_t n_out = 0;
  if (!rd(&n_out, 4)) {
    SetError("truncated response");
    return -1;
  }
  if (n_out < 0 || n_out > 4096) {
    SetError("implausible output count (protocol desync?)");
    return -1;
  }
  auto* outs = static_cast<PD_Tensor*>(std::calloc(n_out, sizeof(PD_Tensor)));
  for (int i = 0; i < n_out; ++i) {
    PD_Tensor& t = outs[i];
    int32_t name_len = 0;
    if (!rd(&name_len, 4) || name_len < 0 || name_len > 4096) {
      SetError("bad tensor name length");
      PD_TensorsFree(outs, i);
      return -1;
    }
    std::string name(name_len, '\0');
    if (!rd(name.data(), name_len)) {
      SetError("truncated tensor name");
      PD_TensorsFree(outs, i);
      return -1;
    }
    std::snprintf(t.name, PD_MAX_NAME, "%s", name.c_str());
    int32_t dtype = 0, ndim = 0;
    if (!rd(&dtype, 4) || !rd(&ndim, 4) || DtypeSize(dtype) == 0 ||
        ndim < 0 || ndim > PD_MAX_RANK) {
      SetError("bad tensor header (dtype/ndim out of range for pd_capi)");
      PD_TensorsFree(outs, i);
      return -1;
    }
    t.dtype = dtype;
    t.ndim = ndim;
    for (int d = 0; d < ndim; ++d) {
      int64_t dim = 0;
      if (!rd(&dim, 8) || dim < 0) {
        SetError("bad tensor dim");
        PD_TensorsFree(outs, i);
        return -1;
      }
      t.shape[d] = dim;
    }
    size_t bytes = static_cast<size_t>(Numel(t)) * DtypeSize(t.dtype);
    t.data = std::malloc(bytes ? bytes : 1);
    if (!rd(t.data, bytes)) {
      SetError("truncated tensor payload");
      PD_TensorsFree(outs, i + 1);
      return -1;
    }
  }
  *outputs = outs;
  *n_outputs = n_out;
  return 0;
}

}  // namespace

struct PD_Predictor {
  pid_t pid = -1;
  int to_worker = -1;    // write end
  int from_worker = -1;  // read end
  long inproc_handle = -1;  // >= 0: embedded-interpreter predictor
};

extern "C" {

PD_Predictor* PD_PredictorCreate(const char* model_path,
                                 const char* python_exe) {
  if (model_path == nullptr) {
    SetError("model_path is NULL");
    return nullptr;
  }
  const char* py = python_exe ? python_exe : "python3";
  int in_pipe[2], out_pipe[2];
  if (pipe(in_pipe) != 0) {
    SetError("pipe() failed");
    return nullptr;
  }
  if (pipe(out_pipe) != 0) {
    SetError("pipe() failed");
    close(in_pipe[0]); close(in_pipe[1]);
    return nullptr;
  }
  pid_t pid = fork();
  if (pid < 0) {
    SetError("fork() failed");
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    return nullptr;
  }
  if (pid == 0) {
    // child: stdin <- in_pipe[0], stdout -> out_pipe[1]
    dup2(in_pipe[0], STDIN_FILENO);
    dup2(out_pipe[1], STDOUT_FILENO);
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    execlp(py, py, "-m", "paddle_tpu.inference.capi_worker", model_path,
           static_cast<char*>(nullptr));
    std::fprintf(stderr, "pd_capi: execlp(%s) failed\n", py);
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  auto* pred = new PD_Predictor;
  pred->pid = pid;
  pred->to_worker = in_pipe[1];
  pred->from_worker = out_pipe[0];
  char ready[4];
  if (!ReadAll(pred->from_worker, ready, 4) ||
      std::memcmp(ready, "PDOK", 4) != 0) {
    SetError("worker failed to start (is paddle_tpu importable by " +
             std::string(py) + "?)");
    PD_PredictorDestroy(pred);
    return nullptr;
  }
  return pred;
}

int PD_PredictorRun(PD_Predictor* pred, const PD_Tensor* inputs, int n_inputs,
                    PD_Tensor** outputs, int* n_outputs) {
  if (!pred || (pred->pid < 0 && pred->inproc_handle < 0)) {
    SetError("invalid predictor");
    return -1;
  }
  if (pred->inproc_handle >= 0) {
    // embedded interpreter: one python call, parse the response bytes
    std::string req;
    SerializeRequest(inputs, n_inputs,
                     [&req](const void* p, size_t len) {
                       req.append(static_cast<const char*>(p), len);
                       return true;
                     });
    if (!EnsurePython()) return -1;
    int g = g_py.GILState_Ensure();
    int rc = -1;
    void* mod = g_py.Import_ImportModule("paddle_tpu.inference.capi_inproc");
    if (!mod) {
      if (g_py.Err_Occurred()) g_py.Err_Print();
      SetError("cannot import paddle_tpu.inference.capi_inproc");
      g_py.GILState_Release(g);
      return -1;
    }
    void* name = g_py.Unicode_FromString("run");
    void* h = g_py.Long_FromLong(pred->inproc_handle);
    void* payload = g_py.Bytes_FromStringAndSize(
        req.data(), static_cast<ssize_t>(req.size()));
    if (!name || !h || !payload) {
      // Py_DecRef is NULL-safe, so partial allocations clean up below;
      // drain the pending MemoryError before releasing the GIL
      if (g_py.Err_Occurred()) g_py.Err_Print();
      g_py.Object_DecRef(payload);
      g_py.Object_DecRef(h);
      g_py.Object_DecRef(name);
      g_py.Object_DecRef(mod);
      SetError("python object allocation failed");
      g_py.GILState_Release(g);
      return -1;
    }
    void* res = g_py.Object_CallMethodObjArgs(mod, name, h, payload, nullptr);
    char* out_p = nullptr;
    ssize_t out_n = 0;
    if (res && g_py.Bytes_AsStringAndSize(res, &out_p, &out_n) == 0) {
      size_t off = 0;
      ReadFn rd = [&](void* dst, size_t len) {
        if (off + len > static_cast<size_t>(out_n)) return false;
        std::memcpy(dst, out_p + off, len);
        off += len;
        return true;
      };
      rc = ParseResponse(rd, outputs, n_outputs);
    } else {
      if (g_py.Err_Occurred()) g_py.Err_Print();
      SetError("in-process run call failed");
    }
    if (res) g_py.Object_DecRef(res);
    g_py.Object_DecRef(payload);
    g_py.Object_DecRef(h);
    g_py.Object_DecRef(name);
    g_py.Object_DecRef(mod);
    g_py.GILState_Release(g);
    return rc;
  }

  int to = pred->to_worker;
  if (!SerializeRequest(inputs, n_inputs,
                        [to](const void* p, size_t len) {
                          return WriteAll(to, p, len);
                        })) {
    SetError("write failed");
    return -1;
  }
  int from = pred->from_worker;
  ReadFn rd = [from](void* dst, size_t len) { return ReadAll(from, dst, len); };
  return ParseResponse(rd, outputs, n_outputs);
}

PD_Predictor* PD_PredictorCreateInProcess(const char* model_path) {
  if (model_path == nullptr) {
    SetError("model_path is NULL");
    return nullptr;
  }
  if (!EnsurePython()) return nullptr;
  int g = g_py.GILState_Ensure();
  void* mod = g_py.Import_ImportModule("paddle_tpu.inference.capi_inproc");
  if (!mod) {
    if (g_py.Err_Occurred()) g_py.Err_Print();
    SetError("cannot import paddle_tpu.inference.capi_inproc "
             "(is paddle_tpu on PYTHONPATH?)");
    g_py.GILState_Release(g);
    return nullptr;
  }
  void* name = g_py.Unicode_FromString("create");
  void* path = g_py.Unicode_FromString(model_path);
  if (!name || !path) {
    if (g_py.Err_Occurred()) g_py.Err_Print();
    g_py.Object_DecRef(path);
    g_py.Object_DecRef(name);
    g_py.Object_DecRef(mod);
    SetError("python object allocation failed");
    g_py.GILState_Release(g);
    return nullptr;
  }
  void* res = g_py.Object_CallMethodObjArgs(mod, name, path, nullptr);
  long handle = -1;
  if (res) {
    handle = g_py.Long_AsLong(res);
    g_py.Object_DecRef(res);
  } else if (g_py.Err_Occurred()) {
    g_py.Err_Print();
  }
  g_py.Object_DecRef(path);
  g_py.Object_DecRef(name);
  g_py.Object_DecRef(mod);
  g_py.GILState_Release(g);
  if (handle < 0) {
    SetError("in-process predictor creation failed");
    return nullptr;
  }
  auto* pred = new PD_Predictor;
  pred->inproc_handle = handle;
  return pred;
}

void PD_TensorsFree(PD_Tensor* tensors, int n) {
  if (!tensors) return;
  for (int i = 0; i < n; ++i) std::free(tensors[i].data);
  std::free(tensors);
}

void PD_PredictorDestroy(PD_Predictor* pred) {
  if (!pred) return;
  if (pred->inproc_handle >= 0 && g_py.ok) {
    int g = g_py.GILState_Ensure();
    void* mod = g_py.Import_ImportModule("paddle_tpu.inference.capi_inproc");
    if (mod) {
      void* name = g_py.Unicode_FromString("destroy");
      void* h = g_py.Long_FromLong(pred->inproc_handle);
      void* res = g_py.Object_CallMethodObjArgs(mod, name, h, nullptr);
      if (res) g_py.Object_DecRef(res);
      g_py.Object_DecRef(h);
      g_py.Object_DecRef(name);
      g_py.Object_DecRef(mod);
    }
    // never leave a pending exception on the (possibly host-owned) thread
    if (g_py.Err_Occurred()) g_py.Err_Print();
    g_py.GILState_Release(g);
  }
  if (pred->to_worker >= 0) close(pred->to_worker);
  if (pred->from_worker >= 0) close(pred->from_worker);
  if (pred->pid > 0) {
    int status = 0;
    // worker exits on stdin EOF; reap it (kill after a grace period is the
    // caller's job if it wants hard deadlines)
    waitpid(pred->pid, &status, 0);
  }
  delete pred;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

}  // extern "C"
