// Runtime stats monitor: named int64 gauges.
//
// Reference parity: paddle/fluid/platform/monitor.h — `StatValue` (:43) and
// `StatRegistry` (:84), the STAT_ADD/STAT_RESET macros used by gpu_info.cc
// and data_feed.cc. Rebuilt as a process-wide registry with a C ABI so both
// the Python layer and native subsystems (datafeed) publish into one place.
#include <atomic>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace pt {

class StatRegistry {
 public:
  static StatRegistry& Instance() {
    static StatRegistry r;
    return r;
  }

  void Add(const std::string& name, long long v) {
    Slot(name)->fetch_add(v, std::memory_order_relaxed);
  }
  void Set(const std::string& name, long long v) {
    Slot(name)->store(v, std::memory_order_relaxed);
  }
  long long Get(const std::string& name) {
    return Slot(name)->load(std::memory_order_relaxed);
  }
  void Reset(const std::string& name) { Slot(name)->store(0); }

  std::string List() {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (auto& kv : stats_) {
      out += kv.first + "=" + std::to_string(kv.second->load()) + "\n";
    }
    return out;
  }

 private:
  std::atomic<long long>* Slot(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = stats_.find(name);
    if (it == stats_.end()) {
      it = stats_.emplace(name, new std::atomic<long long>(0)).first;
    }
    return it->second;
  }
  std::mutex mu_;
  std::map<std::string, std::atomic<long long>*> stats_;
};

}  // namespace pt

extern "C" {

void pt_stat_add(const char* name, long long v) {
  pt::StatRegistry::Instance().Add(name, v);
}
void pt_stat_set(const char* name, long long v) {
  pt::StatRegistry::Instance().Set(name, v);
}
long long pt_stat_get(const char* name) {
  return pt::StatRegistry::Instance().Get(name);
}
void pt_stat_reset(const char* name) {
  pt::StatRegistry::Instance().Reset(name);
}
// Writes "name=value\n" lines into buf; returns bytes needed (caller may
// retry with a bigger buffer).
int pt_stat_list(char* buf, int buflen) {
  std::string s = pt::StatRegistry::Instance().List();
  int need = static_cast<int>(s.size());
  if (buf && buflen > 0) {
    int n = need < buflen - 1 ? need : buflen - 1;
    memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return need;
}

}  // extern "C"
