// Host-side scoped-event profiler with chrome-trace export.
//
// Reference parity: paddle/fluid/platform/profiler.h — `RecordEvent` RAII
// markers (:126), `EnableProfiler`/`DisableProfiler` (:208/:211), the
// aggregated event table of profiler_helper.h, and tools/timeline.py's
// chrome://tracing conversion. The CUPTI device tracer (device_tracer.h:19)
// has no TPU analogue here — device-side traces come from jax.profiler/XLA
// (SURVEY.md §5.1 TPU mapping); this records the host/framework side and can
// be merged with an XLA trace by the Python bridge.
#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace pt {

static inline long long NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Event {
  std::string name;
  long long start_ns;
  long long end_ns;
  unsigned long long tid;
};

static std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  return out;
}

class Profiler {
 public:
  static Profiler& Instance() {
    static Profiler p;
    return p;
  }

  void Enable() {
    std::lock_guard<std::mutex> lk(mu_);
    enabled_ = true;
  }
  void Disable() {
    std::lock_guard<std::mutex> lk(mu_);
    enabled_ = false;
  }
  bool Enabled() {
    std::lock_guard<std::mutex> lk(mu_);
    return enabled_;
  }
  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    events_.clear();
  }

  void Push(const char* name) {
    if (!Enabled()) return;
    Stack().push_back({name, NowNs()});
  }

  // Pops regardless of enabled-state (a disable between push and pop must
  // not strand the open entry on the stack); only records while enabled.
  void Pop() {
    auto& st = Stack();
    if (st.empty()) return;
    auto open = st.back();
    st.pop_back();
    if (!Enabled()) return;
    Event e{std::move(open.first), open.second, NowNs(),
            std::hash<std::thread::id>{}(std::this_thread::get_id())};
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(std::move(e));
  }

  // One complete event straight from the caller (used for externally timed
  // spans, e.g. XLA executable runs surfaced from Python).
  void AddSpan(const char* name, long long start_ns, long long end_ns) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{
        name, start_ns, end_ns,
        std::hash<std::thread::id>{}(std::this_thread::get_id())});
  }

  // chrome://tracing "traceEvents" JSON (ph:X complete events, us units).
  int ExportChrome(const char* path) {
    std::lock_guard<std::mutex> lk(mu_);
    FILE* f = fopen(path, "w");
    if (!f) return -1;
    fputs("{\"traceEvents\":[", f);
    for (size_t i = 0; i < events_.size(); ++i) {
      const Event& e = events_[i];
      fprintf(f,
              "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%llu,"
              "\"ts\":%.3f,\"dur\":%.3f}",
              i ? "," : "", JsonEscape(e.name).c_str(), e.tid,
              e.start_ns / 1000.0, (e.end_ns - e.start_ns) / 1000.0);
    }
    fputs("]}", f);
    fclose(f);
    return static_cast<int>(events_.size());
  }

  // Aggregated text table (profiler_helper.h style), sorted descending by
  // `sorted_key`: one of total (default), calls, max, min, ave — the
  // fluid stop_profiler(sorted_key=...) contract.
  std::string Summary(const char* sorted_key) {
    std::lock_guard<std::mutex> lk(mu_);
    struct Agg {
      long long total = 0, mn = 0, mx = 0;
      long long calls = 0;
    };
    std::map<std::string, Agg> agg;
    for (const auto& e : events_) {
      auto& a = agg[e.name];
      long long d = e.end_ns - e.start_ns;
      a.total += d;
      a.mn = a.calls ? std::min(a.mn, d) : d;
      a.mx = std::max(a.mx, d);
      a.calls++;
    }
    const std::string key = sorted_key ? sorted_key : "total";
    auto rank = [&key](const Agg& a) -> double {
      if (key == "calls") return static_cast<double>(a.calls);
      if (key == "max") return static_cast<double>(a.mx);
      if (key == "min") return static_cast<double>(a.mn);
      if (key == "ave")
        return a.calls ? static_cast<double>(a.total) / a.calls : 0.0;
      return static_cast<double>(a.total);
    };
    std::vector<std::pair<std::string, Agg>> rows(agg.begin(), agg.end());
    std::sort(rows.begin(), rows.end(),
              [&rank](const auto& a, const auto& b) {
                return rank(a.second) > rank(b.second);
              });
    char line[512];
    std::string out =
        "Event                            Calls    Total(ms)    Avg(ms)    "
        "Min(ms)    Max(ms)\n";
    for (const auto& r : rows) {
      snprintf(line, sizeof(line), "%-32s %6lld %12.3f %10.3f %10.3f %10.3f\n",
               r.first.c_str(), r.second.calls, r.second.total / 1e6,
               r.second.total / 1e6 / r.second.calls, r.second.mn / 1e6,
               r.second.mx / 1e6);
      out += line;
    }
    return out;
  }

 private:
  static std::vector<std::pair<std::string, long long>>& Stack() {
    thread_local std::vector<std::pair<std::string, long long>> st;
    return st;
  }
  bool enabled_ = false;
  std::vector<Event> events_;
  std::mutex mu_;
};

}  // namespace pt

extern "C" {

void pt_prof_enable() { pt::Profiler::Instance().Enable(); }
void pt_prof_disable() { pt::Profiler::Instance().Disable(); }
int pt_prof_enabled() { return pt::Profiler::Instance().Enabled() ? 1 : 0; }
void pt_prof_clear() { pt::Profiler::Instance().Clear(); }
void pt_prof_push(const char* name) { pt::Profiler::Instance().Push(name); }
void pt_prof_pop() { pt::Profiler::Instance().Pop(); }
void pt_prof_add_span(const char* name, long long start_ns, long long end_ns) {
  pt::Profiler::Instance().AddSpan(name, start_ns, end_ns);
}
int pt_prof_export_chrome(const char* path) {
  return pt::Profiler::Instance().ExportChrome(path);
}
static int FillSummary(const std::string& s, char* buf, int buflen) {
  int need = static_cast<int>(s.size());
  if (buf && buflen > 0) {
    int n = need < buflen - 1 ? need : buflen - 1;
    memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return need;
}

int pt_prof_summary(char* buf, int buflen) {
  return FillSummary(pt::Profiler::Instance().Summary("total"), buf, buflen);
}

int pt_prof_summary_sorted(const char* sorted_key, char* buf, int buflen) {
  return FillSummary(pt::Profiler::Instance().Summary(sorted_key), buf,
                     buflen);
}

}  // extern "C"
