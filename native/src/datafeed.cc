// In-memory multi-slot data feed: parallel file parsing, global shuffle,
// async fixed-shape batch assembly.
//
// Reference parity: paddle/fluid/framework/data_feed.h — `DataFeed` (:108),
// `MultiSlotDataFeed` (:650), `MultiSlotInMemoryDataFeed` (:668) — plus the
// in-memory sample store with shuffle of framework/data_set.h and the
// double-buffered staging of operators/reader/buffered_reader.cc.
//
// TPU-first redesign rather than a port: the reference's samples are ragged
// (LoD) and batches carry LoD offsets; XLA wants static shapes, so every
// slot here has a FIXED per-sample dim and parsing right-pads/truncates to
// it (the padding/bucketing policy SURVEY.md §7 "hard parts" calls for).
// Batches are assembled into per-slot contiguous [batch, dim] host buffers
// that Python wraps zero-copy as numpy and ships to device in one transfer.
//
// Text format, one sample per line:   slot0_v1,v2,...;slot1_v1,...;...
// (slots ';'-separated in spec order, values ','-separated; int slots parse
// as int64, float slots as float32).
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "pt/channel.h"
#include "pt/threadpool.h"

extern "C" void pt_stat_add(const char* name, long long v);

namespace pt {

enum class SlotType : int { kFloat32 = 0, kInt64 = 1 };

struct SlotSpec {
  std::string name;
  SlotType type;
  int dim;
};

// One sample: per-slot fixed-dim values, stored SoA-per-sample (small) —
// float and int payloads in one buffer each to keep shuffle cheap (moves of
// two vectors, no per-slot allocation churn).
struct Sample {
  std::vector<float> fvals;    // concatenated float slots, spec order
  std::vector<int64_t> ivals;  // concatenated int slots, spec order
};

struct Batch {
  int rows = 0;
  std::vector<float> fdata;    // [rows * total_float_dim]
  std::vector<int64_t> idata;  // [rows * total_int_dim]
};

class DataFeed {
 public:
  DataFeed(std::vector<SlotSpec> slots, int batch_size, int capacity,
           int num_threads)
      : slots_(std::move(slots)),
        batch_size_(batch_size),
        queue_(capacity > 0 ? capacity : 8),
        num_threads_(num_threads > 0 ? num_threads : 4) {
    for (const auto& s : slots_) {
      if (s.type == SlotType::kFloat32)
        float_dim_ += s.dim;
      else
        int_dim_ += s.dim;
    }
  }

  ~DataFeed() { Stop(); }

  void SetFiles(std::vector<std::string> files) { files_ = std::move(files); }

  // data_set.h LoadIntoMemory: parse all files in parallel into samples_.
  int LoadIntoMemory() {
    Stop();  // a running assembler reads samples_; appending may reallocate
    std::vector<std::vector<Sample>> shards(files_.size());
    {
      ThreadPool pool(num_threads_);
      std::vector<std::future<void>> futs;
      std::atomic<int> bad{0};
      for (size_t i = 0; i < files_.size(); ++i) {
        futs.push_back(pool.Run([this, i, &shards, &bad] {
          if (!ParseFile(files_[i], &shards[i])) bad.fetch_add(1);
        }));
      }
      for (auto& f : futs) f.wait();
      if (bad.load()) return -1;
    }
    size_t total = samples_.size();
    for (auto& sh : shards) total += sh.size();
    samples_.reserve(total);
    for (auto& sh : shards) {
      for (auto& s : sh) samples_.push_back(std::move(s));
    }
    pt_stat_add("datafeed.samples_loaded",
                static_cast<long long>(samples_.size()));
    return static_cast<int>(samples_.size());
  }

  // data_set.h LocalShuffle (single-process scope of the reference's
  // global shuffle; cross-host shuffle belongs to the Python sharding layer).
  // Stops any in-flight epoch first: the assembler thread reads samples_.
  void Shuffle(uint64_t seed) {
    Stop();
    std::mt19937_64 rng(seed);
    for (size_t i = samples_.size(); i > 1; --i) {
      std::swap(samples_[i - 1], samples_[rng() % i]);
    }
  }

  int NumSamples() const { return static_cast<int>(samples_.size()); }
  int FloatDim() const { return float_dim_; }
  int IntDim() const { return int_dim_; }

  // Launch the background assembler for one epoch (buffered_reader.cc
  // double-buffering generalized to a bounded channel of ready batches).
  void Start(int drop_last) {
    Stop();
    queue_.Reopen();
    stop_requested_ = false;
    worker_ = std::thread([this, drop_last] {
      const size_t n = samples_.size();
      size_t i = 0;
      while (i < n && !stop_requested_) {
        size_t rows = std::min<size_t>(batch_size_, n - i);
        if (drop_last && rows < static_cast<size_t>(batch_size_)) break;
        Batch b;
        b.rows = static_cast<int>(rows);
        b.fdata.resize(rows * float_dim_);
        b.idata.resize(rows * int_dim_);
        for (size_t r = 0; r < rows; ++r) {
          const Sample& s = samples_[i + r];
          if (float_dim_)
            memcpy(b.fdata.data() + r * float_dim_, s.fvals.data(),
                   float_dim_ * sizeof(float));
          if (int_dim_)
            memcpy(b.idata.data() + r * int_dim_, s.ivals.data(),
                   int_dim_ * sizeof(int64_t));
        }
        i += rows;
        pt_stat_add("datafeed.batches_produced", 1);
        if (!queue_.Put(std::move(b))) return;
      }
      queue_.Close();
    });
    started_ = true;
  }

  // Copy next batch into caller buffers ([batch, total_dim] each, already
  // allocated at full batch_size). Returns rows, or 0 at epoch end.
  int Next(float* fbuf, int64_t* ibuf) {
    Batch b;
    if (!queue_.Get(&b)) return 0;
    if (fbuf && float_dim_)
      memcpy(fbuf, b.fdata.data(), b.fdata.size() * sizeof(float));
    if (ibuf && int_dim_)
      memcpy(ibuf, b.idata.data(), b.idata.size() * sizeof(int64_t));
    return b.rows;
  }

  void ReleaseMemory() {
    Stop();  // assembler thread memcpys out of samples_
    samples_.clear();
    samples_.shrink_to_fit();
  }

 private:
  void Stop() {
    if (started_) {
      stop_requested_ = true;
      queue_.Close();
      if (worker_.joinable()) worker_.join();
      started_ = false;
    }
  }

  bool ParseFile(const std::string& path, std::vector<Sample>* out) {
    std::ifstream in(path);
    if (!in.good()) return false;
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      Sample s;
      s.fvals.assign(float_dim_, 0.0f);
      s.ivals.assign(int_dim_, 0);
      size_t pos = 0;
      int foff = 0, ioff = 0;
      for (const auto& slot : slots_) {
        size_t end = line.find(';', pos);
        std::string field = line.substr(
            pos, end == std::string::npos ? std::string::npos : end - pos);
        pos = end == std::string::npos ? line.size() : end + 1;
        // pad-or-truncate to slot.dim (static-shape policy)
        const char* p = field.c_str();
        char* q = nullptr;
        for (int k = 0; k < slot.dim && *p; ++k) {
          if (slot.type == SlotType::kFloat32) {
            s.fvals[foff + k] = strtof(p, &q);
          } else {
            s.ivals[ioff + k] = strtoll(p, &q, 10);
          }
          if (q == p) break;
          p = (*q == ',') ? q + 1 : q;
        }
        if (slot.type == SlotType::kFloat32)
          foff += slot.dim;
        else
          ioff += slot.dim;
      }
      out->push_back(std::move(s));
    }
    return true;
  }

  std::vector<SlotSpec> slots_;
  int batch_size_;
  int float_dim_ = 0, int_dim_ = 0;
  Channel<Batch> queue_;
  int num_threads_;
  std::vector<std::string> files_;
  std::vector<Sample> samples_;
  std::thread worker_;
  std::atomic<bool> stop_requested_{false};
  bool started_ = false;
};

// slot_spec: "name:f:dim;name:i:dim;..."
static std::vector<SlotSpec> ParseSpec(const char* spec) {
  std::vector<SlotSpec> out;
  std::stringstream ss(spec);
  std::string item;
  while (std::getline(ss, item, ';')) {
    if (item.empty()) continue;
    size_t c1 = item.find(':'), c2 = item.find(':', c1 + 1);
    SlotSpec s;
    s.name = item.substr(0, c1);
    s.type = item[c1 + 1] == 'i' ? SlotType::kInt64 : SlotType::kFloat32;
    s.dim = atoi(item.c_str() + c2 + 1);
    if (s.dim <= 0) return {};  // invalid spec — creation fails loudly
    out.push_back(std::move(s));
  }
  return out;
}

}  // namespace pt

extern "C" {

void* pt_feed_create(const char* slot_spec, int batch_size, int capacity,
                     int num_threads) {
  auto slots = pt::ParseSpec(slot_spec);
  if (slots.empty() || batch_size <= 0) return nullptr;
  return new pt::DataFeed(std::move(slots), batch_size, capacity, num_threads);
}

void pt_feed_set_files(void* h, const char* files) {
  std::vector<std::string> fs;
  std::stringstream ss(files);
  std::string f;
  while (std::getline(ss, f, ';'))
    if (!f.empty()) fs.push_back(f);
  static_cast<pt::DataFeed*>(h)->SetFiles(std::move(fs));
}

int pt_feed_load_into_memory(void* h) {
  return static_cast<pt::DataFeed*>(h)->LoadIntoMemory();
}
void pt_feed_shuffle(void* h, unsigned long long seed) {
  static_cast<pt::DataFeed*>(h)->Shuffle(seed);
}
int pt_feed_num_samples(void* h) {
  return static_cast<pt::DataFeed*>(h)->NumSamples();
}
int pt_feed_float_dim(void* h) {
  return static_cast<pt::DataFeed*>(h)->FloatDim();
}
int pt_feed_int_dim(void* h) {
  return static_cast<pt::DataFeed*>(h)->IntDim();
}
void pt_feed_start(void* h, int drop_last) {
  static_cast<pt::DataFeed*>(h)->Start(drop_last);
}
int pt_feed_next(void* h, float* fbuf, long long* ibuf) {
  return static_cast<pt::DataFeed*>(h)->Next(
      fbuf, reinterpret_cast<int64_t*>(ibuf));
}
void pt_feed_release_memory(void* h) {
  static_cast<pt::DataFeed*>(h)->ReleaseMemory();
}
void pt_feed_destroy(void* h) { delete static_cast<pt::DataFeed*>(h); }

}  // extern "C"
