"""Vision benchmarks: ResNet-50 (BASELINE config 2) and YOLOv3 (config 4,
single-chip part) training throughput in images/sec/chip, plus the r06
static-graph INFERENCE ladder:

* ``conv_infer`` — a conv/BN/pool tower served through the Executor with
  ``opt_passes=default`` ON (the r06 default for inference benches),
  reporting the traced-op-count delta from the rewrite pipeline and the
  first-step compile-time delta vs the unoptimized program;
* ``int8_infer`` — the same tower PTQ'd (slim/quant_static.py) and folded
  to int8 ops by the ``quant_infer`` pass (static/passes.py
  QUANT_INFER_PIPELINE), reporting quantized throughput vs float and the
  int8-vs-float error.  On TPU the quant ops dispatch to the
  ops/pallas/int8 kernels; off-TPU the simulate fallback runs, so CPU
  numbers measure the pass pipeline, not the MXU.

Reference configs: PaddleClas ResNet-50 dygraph (224x224, momentum SGD) and
PaddleDetection YOLOv3-DarkNet53 (416x416, yolo_loss over 3 heads).  No
published in-tree reference numbers exist (BASELINE.md `"published": {}`);
the first TPU measurement recorded here is the baseline.

Usage: python bench_vision.py [resnet50|yolov3|conv_infer|int8_infer|all]
Prints one JSON line per model (same schema as bench.py).
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import autograd
from paddle_tpu.autograd import parameters_dict
from paddle_tpu.optimizer import Momentum
import paddle_tpu.nn.functional as F

# fwd FLOPs per image (2 x MACs, the convention behind the usual
# "ResNet-50 = 4.1 GFLOPs @224", "YOLOv3 = 65.9 BFLOPs @416" numbers);
# training ~= 3x forward (fwd + dW + dX)
_FWD_FLOPS = {"resnet50": 4.09e9, "yolov3": 65.86e9}
_PEAK = {"tpu": 197e12}  # v5e bf16 peak per chip

# First recorded TPU measurements (r04, BENCH_VISION.json) are the
# baselines; vs_baseline tracks progress against them (env-overridable,
# the bench.py convention).
_BASELINE_IPS = {
    "resnet50": float(os.environ.get("BENCH_BASELINE_RESNET", "")
                      or 2096.98),
    "yolov3": float(os.environ.get("BENCH_BASELINE_YOLO", "") or 282.95),
}


def _cast_tree(p, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, p)


def _aot_step(step, example):
    """AOT-compile the jitted step against the bench inputs (the same
    compile the first jit dispatch would do) so the artifact the loop runs
    is also the xprof attribution source (BENCH_PROFILE=0 skips)."""
    if os.environ.get("BENCH_PROFILE", "1") == "0":
        return step, None
    try:
        aot = step.lower(*example).compile()
        return aot, aot
    except Exception:
        return step, None


def _roofline_block(aot, measured_ms):
    """Condensed xprof block for the bench JSON line: per-layer regions
    (Layer named scopes), MFU, and the top memory-bound regions by name —
    the ResNet MFU-gap diagnosis the ROADMAP asks for."""
    from paddle_tpu.utils import xprof

    try:
        report = xprof.profile_aot(aot, measured_ms=measured_ms)
        return xprof.summarize(report, top=5)
    except Exception:
        return None


def _bench_loop(step, params, opt_state, feed, warmup, iters, sync_every):
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, *feed)
        float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, opt_state, loss = step(params, opt_state, *feed)
        if (i + 1) % sync_every == 0 or i == iters - 1:
            float(loss)  # bounded dispatch depth over the axon tunnel
    return time.perf_counter() - t0, float(loss)


def bench_resnet50(on_tpu):
    from paddle_tpu.vision import models as M

    batch = int(os.environ.get("BENCH_RESNET_BATCH", "256" if on_tpu
                               else "8"))
    size = 224 if on_tpu else 64
    # NHWC is the TPU-native layout (channels on the 128-lane minor dim;
    # measured r05 ladder) — overridable for A/B via BENCH_RESNET_LAYOUT
    layout = os.environ.get("BENCH_RESNET_LAYOUT", "NHWC" if on_tpu
                            else "NCHW")
    warmup, iters = (3, int(os.environ.get("BENCH_ITERS", "30"))) \
        if on_tpu else (1, 3)
    model = M.resnet50(num_classes=1000, data_format=layout)
    model.train()
    opt = Momentum(learning_rate=0.1, momentum=0.9)
    params = parameters_dict(model)
    opt_state = opt.init(params)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32

    def train_step(p, s, images, labels):
        def loss_fn(p_):
            logits = autograd.functional_call(
                model, _cast_tree(p_, compute_dtype), (images,))
            with jax.named_scope("loss"):
                return jnp.mean(F.cross_entropy(logits.astype(jnp.float32),
                                                labels))

        loss, grads = jax.value_and_grad(loss_fn)(p)
        with jax.named_scope("optimizer"):
            p, s = opt.update(grads, s, p)
        return p, s, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    shape = ((batch, 3, size, size) if layout == "NCHW"
             else (batch, size, size, 3))
    images = jnp.asarray(rng.standard_normal(shape), compute_dtype)
    labels = jnp.asarray(rng.integers(0, 1000, (batch, 1)), jnp.int32)
    step, aot = _aot_step(step, (params, opt_state, images, labels))
    dt, loss = _bench_loop(step, params, opt_state, (images, labels),
                           warmup, iters,
                           int(os.environ.get("BENCH_SYNC_EVERY", "10")))
    return dict(metric="resnet50_train_throughput", batch=batch,
                imgs_per_sec=batch * iters / dt, iters=iters, loss=loss,
                model="resnet50", size=size, layout=layout, _aot=aot)


def bench_yolov3(on_tpu):
    from paddle_tpu.vision.models.yolov3 import yolov3_darknet53

    # b64 amortizes the step's fixed costs that bound b32 (r05 ladder:
    # 315 -> 360 imgs/s, MFU 0.361)
    batch = int(os.environ.get("BENCH_YOLO_BATCH", "64" if on_tpu else "2"))
    size = 416 if on_tpu else 128
    n_gt = 16
    warmup, iters = (3, int(os.environ.get("BENCH_ITERS", "20"))) \
        if on_tpu else (1, 2)
    model = yolov3_darknet53(num_classes=80)
    model.train()
    opt = Momentum(learning_rate=1e-4, momentum=0.9)
    params = parameters_dict(model)
    opt_state = opt.init(params)
    compute_dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # bf16 head inputs to the loss measured NEUTRAL on throughput (r05
    # ladder) and yolo_loss promotes its grid math to fp32 either way,
    # so feed fp32 heads; BENCH_YOLO_LOSS_DTYPE remains for A/B
    loss_dtype = jnp.dtype(os.environ.get("BENCH_YOLO_LOSS_DTYPE", "")
                           or jnp.float32)

    def train_step(p, s, images, gt_box, gt_label):
        def loss_fn(p_):
            heads = autograd.functional_call(
                model, _cast_tree(p_, compute_dtype), (images,))
            heads = [h.astype(loss_dtype) for h in heads]
            with jax.named_scope("loss"):
                return model.loss(heads, gt_box, gt_label)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        with jax.named_scope("optimizer"):
            p, s = opt.update(grads, s, p)
        return p, s, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal((batch, 3, size, size)),
                         compute_dtype)
    # normalized cx/cy/w/h gt boxes (the yolo_loss contract)
    wh = rng.uniform(0.05, 0.4, (batch, n_gt, 2))
    cxy = rng.uniform(0.2, 0.8, (batch, n_gt, 2))
    gt_box = jnp.asarray(np.concatenate([cxy, wh], -1), jnp.float32)
    gt_label = jnp.asarray(rng.integers(0, 80, (batch, n_gt)), jnp.int32)
    step, aot = _aot_step(step, (params, opt_state, images, gt_box, gt_label))
    dt, loss = _bench_loop(step, params, opt_state,
                           (images, gt_box, gt_label), warmup, iters,
                           int(os.environ.get("BENCH_SYNC_EVERY", "5")))
    return dict(metric="yolov3_train_throughput", batch=batch,
                imgs_per_sec=batch * iters / dt, iters=iters, loss=loss,
                model="yolov3", size=size, _aot=aot)


# ---------------------------------------------------------------------------
# r06 inference ladder: opt_passes-on conv tower + int8 PTQ path
# ---------------------------------------------------------------------------

def _conv_tower(on_tpu):
    """Static conv/BN(relu)/pool x2 + fc head — big enough on TPU for the
    Pallas gates (C=128 lanes), tiny on CPU so the bench rides CI."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers as L

    ch = 128 if on_tpu else 8
    size = 32 if on_tpu else 8
    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = 11
    with static.program_guard(main, startup):
        img = L.data("img", [3, size, size])
        h = L.conv2d(img, ch, 3, padding=1)
        h = L.batch_norm(h, act="relu", is_test=True)
        h = L.pool2d(h, 2, "max", 2)
        h = L.conv2d(h, ch, 3, padding=1)
        h = L.batch_norm(h, act="relu", is_test=True)
        h = L.pool2d(h, 2, "max", 2)
        out = L.fc(L.flatten(h), 10)
    return main, startup, out, size


def _infer_loop(exe, program, feed, fetch, scope, warmup, iters):
    """(first-step ms, steady imgs/sec) for one Executor config."""
    import paddle_tpu.static as static

    with static.scope_guard(scope):
        t0 = time.perf_counter()
        exe.run(program, feed=feed, fetch_list=fetch)
        first_ms = (time.perf_counter() - t0) * 1e3
        for _ in range(warmup):
            exe.run(program, feed=feed, fetch_list=fetch)
        t0 = time.perf_counter()
        for _ in range(iters):
            out, = exe.run(program, feed=feed, fetch_list=fetch)
        dt = time.perf_counter() - t0
    batch = next(iter(feed.values())).shape[0]
    return first_ms, batch * iters / dt, out


def bench_conv_infer(on_tpu):
    import paddle_tpu.static as static
    from paddle_tpu.core import flags
    from paddle_tpu.static import passes as P

    batch = 64 if on_tpu else 8
    warmup, iters = (3, int(os.environ.get("BENCH_ITERS", "30"))) \
        if on_tpu else (1, 5)
    main, startup, out, size = _conv_tower(on_tpu)
    rng = np.random.default_rng(0)
    feed = {"img": rng.standard_normal(
        (batch, 3, size, size)).astype(np.float32)}

    # traced-op-count delta straight from the pipeline the flag runs
    _rw, report = P.PassManager(P.DEFAULT_PIPELINE).apply(
        main, feed_names={"img"}, fetch_names=[out.name])

    saved = flags.get_flags(["opt_passes"])
    results = {}
    try:
        for mode in ("", "default"):
            flags.set_flags({"opt_passes": mode})
            scope = static.Scope()
            with static.scope_guard(scope):
                exe = static.Executor()
                exe.run(startup)
            results[mode or "off"] = _infer_loop(
                exe, main, feed, [out], scope, warmup, iters)
    finally:
        flags.set_flags(saved)
    first_off, ips_off, ref = results["off"]
    first_on, ips_on, got = results["default"]
    err = float(np.abs(np.asarray(got) - np.asarray(ref)).max())
    return dict(metric="conv_infer_throughput", imgs_per_sec=ips_on,
                model="conv_infer", batch=batch, size=size, iters=iters,
                ops_traced_before=report.ops_before,
                ops_traced_after=report.ops_after,
                compile_ms={"opt_off": round(first_off, 1),
                            "opt_on": round(first_on, 1)},
                vs_opt_off=round(ips_on / ips_off, 4),
                opt_abs_err=err)


def bench_int8_infer(on_tpu):
    import paddle_tpu.static as static
    from paddle_tpu.slim import quant_static
    from paddle_tpu.static import passes as P

    batch = 64 if on_tpu else 8
    warmup, iters = (3, int(os.environ.get("BENCH_ITERS", "30"))) \
        if on_tpu else (1, 5)
    main, startup, out, size = _conv_tower(on_tpu)
    rng = np.random.default_rng(0)
    feed = {"img": rng.standard_normal(
        (batch, 3, size, size)).astype(np.float32)}

    scope = static.Scope()
    with static.scope_guard(scope):
        exe = static.Executor()
        exe.run(startup)
    # float baseline BEFORE PTQ mutates the weights in scope
    _first, ips_f32, float_out = _infer_loop(exe, main, feed, [out], scope,
                                             warmup, iters)
    with static.scope_guard(scope):
        ptq = quant_static.PostTrainingQuantization(
            exe, program=main, feed_names=["img"],
            batch_generator=lambda: iter([feed]), batch_nums=1, scope=scope)
        qprog = ptq.quantize()
    rewritten, _report = P.PassManager(P.QUANT_INFER_PIPELINE).apply(
        qprog, feed_names={"img"}, fetch_names=[out.name])
    quant_ops = sum(1 for op in rewritten.global_block().ops
                    if op.type.startswith("quant_"))
    first_ms, ips_q, q_out = _infer_loop(exe, rewritten, feed, [out.name],
                                         scope, warmup, iters)
    scale = float(np.abs(np.asarray(float_out)).max()) or 1.0
    err = float(np.abs(np.asarray(q_out)
                       - np.asarray(float_out)).max()) / scale
    return dict(metric="int8_infer_throughput", imgs_per_sec=ips_q,
                model="int8_infer", batch=batch, size=size, iters=iters,
                quant_ops=quant_ops, compile_ms=round(first_ms, 1),
                vs_f32=round(ips_q / ips_f32, 4),
                int8_rel_err=round(err, 5))


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    runs = {"resnet50": bench_resnet50, "yolov3": bench_yolov3,
            "conv_infer": bench_conv_infer, "int8_infer": bench_int8_infer}
    if which != "all" and which not in runs:
        sys.exit(f"usage: bench_vision.py [{'|'.join(runs)}|all] "
                 f"(got {which!r})")
    targets = list(runs) if which == "all" else [which]
    for name in targets:
        r = runs[name](on_tpu)
        ips = r.pop("imgs_per_sec")
        mfu = None
        if name in _FWD_FLOPS and platform in _PEAK:
            flops = 3 * _FWD_FLOPS[name] * (r["size"] / (224 if name ==
                                            "resnet50" else 416)) ** 2
            mfu = round(ips * flops / _PEAK[platform], 4)
        loss = r.pop("loss", None)
        aot = r.pop("_aot", None)
        roofline = (_roofline_block(aot, measured_ms=1000.0 * r["batch"] / ips)
                    if aot is not None else None)
        line = {
            "metric": r.pop("metric"),
            "value": round(ips, 2),
            "unit": "imgs/sec/chip",
            "platform": platform,
            "mfu_est": mfu,
            **r,
        }
        if name in _BASELINE_IPS:
            line["vs_baseline"] = round(ips / _BASELINE_IPS[name], 4)
            # NaN would break the one-JSON-line contract
            line["loss"] = round(loss, 4) \
                if loss is not None and np.isfinite(loss) else None
            line["roofline"] = roofline
        print(json.dumps(line))


if __name__ == "__main__":
    main()
